"""Fused op surface (parity: python/paddle/incubate/nn/functional/ —
fused_rms_norm, fused_rotary_position_embedding, swiglu, fused_matmul_bias,
fused_moe, masked/block multihead attention).

On TPU "fused" means XLA fusion or a Pallas kernel — the API contract is what
matters; implementations route to the ops/kernels layers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ....ops.creation import _t
from ....ops.dispatch import apply


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None,
                   quant_scale=-1, **kw):
    from ....nn import functional as F

    def fn(v, w, *rest):
        i = 0
        res = None
        b = None
        if residual is not None:
            res = rest[i]
            i += 1
        if bias is not None:
            b = rest[i]
        if b is not None:
            v = v + b
        if res is not None:
            v = v + res
        var = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (v.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)).astype(v.dtype)
        out = out * w
        if norm_bias is not None:
            out = out + norm_bias._value
        return out

    args = [_t(x), _t(norm_weight)]
    if residual is not None:
        args.append(_t(residual))
    if bias is not None:
        args.append(_t(bias))
    return apply("fused_rms_norm", fn, *args)


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, **kw):
    from ....nn import functional as F

    return F.layer_norm(x, [x.shape[-1]], norm_weight, norm_bias, epsilon)


def swiglu(x, y=None, name=None):
    """parity: incubate/nn/functional/swiglu — silu(x) * y (or split x)."""
    if y is None:
        def fn(v):
            a, b = jnp.split(v, 2, axis=-1)
            return jax.nn.silu(a) * b

        return apply("swiglu", fn, _t(x))
    return apply("swiglu", lambda a, b: jax.nn.silu(a) * b, _t(x), _t(y))


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """parity: incubate/nn/functional/fused_rotary_position_embedding.
    Inputs [batch, seq, heads, head_dim]."""

    def rope_one(x_val, sin_val, cos_val):
        if use_neox_rotary_style:
            x1, x2 = jnp.split(x_val, 2, axis=-1)
            rotated = jnp.concatenate([-x2, x1], axis=-1)
            return x_val * cos_val + rotated * sin_val
        x1 = x_val[..., 0::2]
        x2 = x_val[..., 1::2]
        rot = jnp.stack([-x2, x1], axis=-1).reshape(x_val.shape)
        return x_val * cos_val + rot * sin_val

    def make_sincos(x_val):
        seq = x_val.shape[1]
        dim = x_val.shape[-1]
        inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, dim, 2,
                                                    dtype=jnp.float32) / dim))
        t = jnp.arange(seq, dtype=jnp.float32)
        freqs = jnp.outer(t, inv)
        emb = jnp.concatenate([freqs, freqs], axis=-1)
        return (jnp.sin(emb)[None, :, None, :].astype(x_val.dtype),
                jnp.cos(emb)[None, :, None, :].astype(x_val.dtype))

    outs = []
    for t_in in (q, k, v):
        if t_in is None:
            outs.append(None)
            continue
        if sin is not None and cos is not None:
            def fn(v_, s_, c_):
                s_ = s_.reshape(1, s_.shape[-2], 1, s_.shape[-1]) if s_.ndim != 4 else s_
                c_ = c_.reshape(1, c_.shape[-2], 1, c_.shape[-1]) if c_.ndim != 4 else c_
                return rope_one(v_, s_.astype(v_.dtype), c_.astype(v_.dtype))

            outs.append(apply("fused_rope", fn, _t(t_in), _t(sin), _t(cos)))
        else:
            def fn(v_):
                s_, c_ = make_sincos(v_)
                return rope_one(v_, s_, c_)

            outs.append(apply("fused_rope", fn, _t(t_in)))
    return tuple(outs)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    from ....ops.linalg import matmul

    out = matmul(x, y, transpose_x, transpose_y)
    if bias is not None:
        out = out + bias
    return out


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation="gelu"):
    from ....nn import functional as F

    out = fused_matmul_bias(x, y, bias, trans_x, trans_y)
    return getattr(F, activation)(out)


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.0, ln_epsilon=1e-5,
                                           training=True, **kw):
    from ....nn import functional as F

    out = x if bias is None else x + bias
    if dropout_rate:
        out = F.dropout(out, dropout_rate, training=training)
    out = out + residual
    return F.layer_norm(out, [out.shape[-1]], ln_scale, ln_bias, ln_epsilon)


def fused_dropout_add(x, y, p=0.0, training=True, mode="upscale_in_train",
                      name=None):
    from ....nn import functional as F

    return F.dropout(x, p, training=training, mode=mode) + y


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               sequence_lengths=None, rotary_tensor=None,
                               beam_cache_offset=None, qkv_out_scale=None,
                               out_shift=None, out_smooth=None, seq_len=1,
                               rotary_emb_dims=0, use_neox_rotary_style=False,
                               compute_dtype="default", **kw):
    """Single-token decode attention over a KV cache (parity:
    incubate/nn/functional/masked_multihead_attention — the reference's
    fused decode kernel). x: [B, 3*H*D] packed qkv for ONE step;
    cache_kv: [2, B, H, max_len, D]; sequence_lengths: [B] current lengths.
    Returns (out [B, H*D], updated cache_kv)."""
    import jax
    import jax.numpy as jnp
    import math as _math

    from ....core.tensor import Tensor
    from ....ops.creation import _t
    from ....ops.dispatch import apply

    def fn(xv, cache, seqlens):
        B = xv.shape[0]
        _, _, H, max_len, D = cache.shape
        qkv = xv.reshape(B, 3, H, D)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        pos = seqlens.astype(jnp.int32)                      # [B]
        bidx = jnp.arange(B)
        kc = cache[0].at[bidx, :, pos].set(k)                # [B,H,max,D]
        vc = cache[1].at[bidx, :, pos].set(v)
        s = jnp.einsum("bhd,bhkd->bhk", q, kc,
                       preferred_element_type=jnp.float32)
        s = s / _math.sqrt(D)
        mask = jnp.arange(max_len)[None, None, :] <= pos[:, None, None]
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, -1).astype(vc.dtype)
        out = jnp.einsum("bhk,bhkd->bhd", p, vc)
        return out.reshape(B, H * D), jnp.stack([kc, vc])

    seqlens = sequence_lengths if sequence_lengths is not None else None
    out, new_cache = apply("masked_multihead_attention", fn, _t(x),
                           _t(cache_kv), _t(seqlens))
    return out, new_cache


def block_multihead_attention(qkv, key_cache, value_cache, seq_lens_encoder,
                              seq_lens_decoder, seq_lens_this_time,
                              padding_offsets=None, cum_offsets=None,
                              cu_seqlens_q=None, cu_seqlens_k=None,
                              block_tables=None, max_seq_len=None, **kw):
    """Blocked KV-cache attention for batched decode (parity:
    incubate/nn/functional/block_multihead_attention — the reference's paged
    decode kernel over cutlass). Simplified contract: qkv [B, 3, H, D] one
    step per sequence; caches [B, H, max_len, D]; seq_lens_decoder [B]."""
    import jax
    import jax.numpy as jnp
    import math as _math

    from ....ops.creation import _t
    from ....ops.dispatch import apply

    def fn(qkvv, kc, vc, lens):
        B, _, H, D = qkvv.shape
        q, k, v = qkvv[:, 0], qkvv[:, 1], qkvv[:, 2]
        pos = lens.astype(jnp.int32)
        bidx = jnp.arange(B)
        kc = kc.at[bidx, :, pos].set(k)
        vc = vc.at[bidx, :, pos].set(v)
        s = jnp.einsum("bhd,bhkd->bhk", q, kc,
                       preferred_element_type=jnp.float32) / _math.sqrt(D)
        mask = jnp.arange(kc.shape[2])[None, None, :] <= pos[:, None, None]
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, -1).astype(vc.dtype)
        out = jnp.einsum("bhk,bhkd->bhd", p, vc)
        return out, kc, vc

    return apply("block_multihead_attention", fn, _t(qkv), _t(key_cache),
                 _t(value_cache), _t(seq_lens_decoder))


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn2_bias=None, quant_method="None", moe_topk=2, norm_topk_prob=True,
              **kw):
    """Fused MoE FFN (parity: incubate/nn/functional/fused_moe.py:75 over the
    cutlass grouped-GEMM kernels). x: [T, h]; gate_weight [h, E];
    ffn1_weight [E, h, 2f] (gate+up packed) or [E, h, f]; ffn2 [E, f, h]."""
    import jax
    import jax.numpy as jnp

    from ....core.tensor import Tensor
    from ....models.moe import MoEConfig, moe_ffn
    from ....ops.creation import _t
    from ....ops.dispatch import apply

    def fn(xv, gw, w1, w2):
        E = gw.shape[-1]
        f2 = w1.shape[-1]
        if f2 % 2 == 0:
            gate_w, up_w = w1[..., :f2 // 2], w1[..., f2 // 2:]
        else:
            gate_w = up_w = w1
        cfg = MoEConfig(num_experts=E, top_k=moe_topk,
                        hidden_size=xv.shape[-1],
                        moe_intermediate_size=w2.shape[1],
                        capacity_factor=float(E))
        y, _aux = moe_ffn(xv, gw, gate_w, up_w, w2, cfg)
        return y

    return apply("fused_moe", fn, _t(x), _t(gate_weight), _t(ffn1_weight),
                 _t(ffn2_weight))


def fused_bias_act(x, bias=None, dequant_scales=None, shift=None, smooth=None,
                   act_method="gelu", compute_dtype="default", quant_scale=-1,
                   quant_round_type=0, quant_max_bound=0, quant_min_bound=0,
                   name=None):
    """parity: incubate/nn/functional/fused_bias_act — bias + activation in
    one XLA fusion."""
    import jax

    from ....ops.creation import _t
    from ....ops.dispatch import apply

    acts = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
            "silu": jax.nn.silu, "swish": jax.nn.silu,
            "swiglu": None, "geglu": None, "identity": lambda v: v}
    if act_method in ("swiglu", "geglu"):
        inner = jax.nn.silu if act_method == "swiglu" else jax.nn.gelu

        def fn(v, *b):
            if b:
                v = v + b[0]
            a, g = jnp.split(v, 2, axis=-1)
            return inner(a) * g
    else:
        act = acts[act_method]

        def fn(v, *b):
            if b:
                v = v + b[0]
            return act(v)

    args = [_t(x)] + ([_t(bias)] if bias is not None else [])
    return apply("fused_bias_act", fn, *args)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode=
                      "upscale_in_train", ring_id=-1, name=None):
    """parity: incubate fused_feedforward — LN → linear → act → dropout →
    linear → dropout → residual (+LN), fused by XLA."""
    from ....nn import functional as F

    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, x.shape[-1:], ln1_scale, ln1_bias, ln1_epsilon)
    x = F.linear(x, linear1_weight, linear1_bias)
    x = getattr(F, activation)(x)
    x = F.dropout(x, dropout1_rate, training=training, mode=mode)
    x = F.linear(x, linear2_weight, linear2_bias)
    x = F.dropout(x, dropout2_rate, training=training, mode=mode)
    x = x + residual
    if not pre_layer_norm:
        x = F.layer_norm(x, x.shape[-1:], ln2_scale, ln2_bias, ln2_epsilon)
    return x


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.5,
                               attn_dropout_rate=0.5, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, num_heads=None,
                               transpose_qkv_wb=False, name=None):
    """parity: incubate fused_multi_head_attention — fused QKV projection +
    SDPA + output projection (+ residual/LN)."""
    import jax

    from ....core.tensor import Tensor
    from ....nn import functional as F
    from ....ops.creation import _t

    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, x.shape[-1:], pre_ln_scale, pre_ln_bias,
                         pre_ln_epsilon)
    xv = _t(x)._value
    wv = _t(qkv_weight)._value
    B, S, E = xv.shape
    if transpose_qkv_wb:
        # [E, 3*E] layout: heads cannot be inferred from the weight
        if num_heads is None:
            raise ValueError(
                "fused_multi_head_attention: num_heads is required when "
                "transpose_qkv_wb=True")
        H = num_heads
        qkv = xv @ wv
        qkv = qkv.reshape(B, S, 3, H, E // H)
    else:
        # reference layout [3, H, head_dim, E]
        _, H, D, _ = wv.shape
        qkv = jnp.einsum("bse,thde->bsthd", xv, wv)
    if qkv_bias is not None:
        bv = _t(qkv_bias)._value.reshape(3, -1, qkv.shape[-1]) \
            if not transpose_qkv_wb else \
            _t(qkv_bias)._value.reshape(3, qkv.shape[-2], qkv.shape[-1])
        qkv = qkv + bv[None, None]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    D = q.shape[-1]
    scores = jnp.einsum("bshd,bthd->bhst", q, k) / np.sqrt(D)
    if attn_mask is not None:
        scores = scores + _t(attn_mask)._value
    probs = jax.nn.softmax(scores.astype(jnp.float32), -1).astype(q.dtype)
    if training and attn_dropout_rate:
        from ....framework.random import next_key

        keep = jax.random.bernoulli(next_key(), 1 - attn_dropout_rate,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1 - attn_dropout_rate), 0)
    out = jnp.einsum("bhst,bthd->bshd", probs, v).reshape(B, S, -1)
    out = Tensor(out)
    out = F.linear(out, linear_weight, linear_bias)
    out = F.dropout(out, dropout_rate, training=training, mode=mode)
    if add_residual:
        out = out + residual
    if not pre_layer_norm:
        out = F.layer_norm(out, out.shape[-1:], ln_scale, ln_bias,
                           ln_epsilon)
    return out


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-5, cache_kvs=None, pre_caches=None,
                            seq_lens=None, rotary_embs=None, beam_offset=None,
                            time_step=None, attn_mask=None,
                            dropout_rate=0.0, rotary_emb_dims=0,
                            activation="gelu", training=False,
                            mode="upscale_in_train", trans_qkvw=True,
                            ring_id=-1, name=None):
    """parity: incubate fused_multi_transformer — a stack of fused decoder
    layers (the serving fast path). Composes the fused attention + FFN per
    layer; XLA fuses each block."""
    out = x
    n_layers = len(qkv_weights)
    for i in range(n_layers):
        ln_kw = (dict(pre_ln_scale=ln_scales[i],
                      pre_ln_bias=ln_biases[i] if ln_biases else None)
                 if pre_layer_norm else
                 dict(ln_scale=ln_scales[i],
                      ln_bias=ln_biases[i] if ln_biases else None))
        out = fused_multi_head_attention(
            out, qkv_weights[i], linear_weights[i],
            pre_layer_norm=pre_layer_norm,
            qkv_bias=qkv_biases[i] if qkv_biases else None,
            linear_bias=linear_biases[i] if linear_biases else None,
            attn_mask=attn_mask, dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate, training=training, **ln_kw)
        ffn_kw = (dict(ln1_scale=ffn_ln_scales[i],
                       ln1_bias=ffn_ln_biases[i] if ffn_ln_biases else None)
                  if pre_layer_norm else
                  dict(ln2_scale=ffn_ln_scales[i],
                       ln2_bias=ffn_ln_biases[i] if ffn_ln_biases else None))
        out = fused_feedforward(
            out, ffn1_weights[i], ffn2_weights[i],
            linear1_bias=ffn1_biases[i] if ffn1_biases else None,
            linear2_bias=ffn2_biases[i] if ffn2_biases else None,
            dropout1_rate=dropout_rate, dropout2_rate=dropout_rate,
            activation=activation, pre_layer_norm=pre_layer_norm,
            training=training, **ffn_kw)
    return out


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size,
                     name=None):
    """parity: incubate blha_get_max_len — max sequence lengths feeding
    block_multihead_attention."""
    from ....core.tensor import Tensor
    from ....ops.creation import _t

    enc = jnp.max(_t(seq_lens_encoder)._value)
    dec = jnp.max(_t(seq_lens_decoder)._value)
    return Tensor(enc), Tensor(dec)


def variable_length_memory_efficient_attention(
        query, key, value, seq_lens, kv_seq_lens, mask=None, scale=None,
        causal=False, pre_cache_length=0, name=None):
    """parity: incubate variable_length_memory_efficient_attention —
    [B, H, S, D] layout with per-batch valid lengths."""
    import jax

    from ....core.tensor import Tensor
    from ....ops.creation import _t

    q = _t(query)._value
    k = _t(key)._value
    v = _t(value)._value
    B, H, S, D = q.shape
    Sk = k.shape[2]
    sl = _t(seq_lens)._value.reshape(-1)
    kl = _t(kv_seq_lens)._value.reshape(-1)
    sc = scale if scale is not None else 1.0 / np.sqrt(D)
    if k.shape[1] != H:
        k = jnp.repeat(k, H // k.shape[1], axis=1)
        v = jnp.repeat(v, H // v.shape[1], axis=1)
    scores = jnp.einsum("bhsd,bhtd->bhst", q, k) * sc
    valid_q = jnp.arange(S)[None, :] < sl[:, None]       # [B, S]
    valid_k = jnp.arange(Sk)[None, :] < kl[:, None]      # [B, Sk]
    allow = valid_q[:, None, :, None] & valid_k[:, None, None, :]
    if causal:
        # align the causal diagonal with per-batch kv lengths: query i (of
        # sl valid positions) may attend keys j <= i + (kl - sl)
        offs = (kl - sl)[:, None, None, None]
        qi = jnp.arange(S)[None, None, :, None]
        kj = jnp.arange(Sk)[None, None, None, :]
        allow = allow & (kj <= qi + offs)
    if mask is not None:
        scores = scores + _t(mask)._value
    scores = jnp.where(allow, scores, -1e30)
    probs = jax.nn.softmax(scores.astype(jnp.float32), -1).astype(q.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, v)
    out = jnp.where(valid_q[:, None, :, None], out, 0)
    return Tensor(out)
