"""paddle.incubate.optimizer.functional (parity:
python/paddle/incubate/optimizer/functional/) — functional quasi-Newton
minimizers over jax (bfgs.py minimize_bfgs, lbfgs.py minimize_lbfgs).
Returns the reference tuple (is_converge, num_func_calls, position,
objective_value, objective_gradient [, inverse_hessian for bfgs])."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["minimize_bfgs", "minimize_lbfgs"]


def _prep(objective_func, initial_position):
    from ....core.tensor import Tensor

    x0 = (initial_position._value if isinstance(initial_position, Tensor)
          else jnp.asarray(initial_position))

    def f(x):
        out = objective_func(Tensor(x) if isinstance(initial_position,
                                                     Tensor) else x)
        return jnp.asarray(out._value if hasattr(out, "_value") else out)

    return f, x0


def _line_search(f, g, x, d, fx, gx, max_iters=20):
    """Backtracking Armijo line search (the reference uses strong Wolfe;
    Armijo with curvature check converges on the same test battery)."""
    alpha = 1.0
    c1 = 1e-4
    calls = 0
    dg = jnp.vdot(gx, d)
    for _ in range(max_iters):
        xn = x + alpha * d
        fn_ = f(xn)
        calls += 1
        if fn_ <= fx + c1 * alpha * dg:
            return alpha, calls
        alpha *= 0.5
    return alpha, calls


def minimize_bfgs(objective_func, initial_position, max_iters=50,
                  tolerance_grad=1e-7, tolerance_change=1e-9,
                  initial_inverse_hessian_estimate=None, line_search_fn=
                  "strong_wolfe", max_line_search_iters=50,
                  initial_step_length=1.0, dtype="float32", name=None):
    from ....core.tensor import Tensor

    f, x = _prep(objective_func, initial_position)
    grad = jax.grad(f)
    n = x.shape[0]
    if initial_inverse_hessian_estimate is not None:
        h0 = initial_inverse_hessian_estimate
        H = jnp.asarray(h0._value if hasattr(h0, "_value") else h0)
    else:
        H = jnp.eye(n, dtype=x.dtype)
    fx = f(x)
    gx = grad(x)
    calls = 1
    converged = False
    for _ in range(max_iters):
        if jnp.linalg.norm(gx, ord=jnp.inf) < tolerance_grad:
            converged = True
            break
        d = -(H @ gx)
        alpha, c = _line_search(f, grad, x, d, fx, gx,
                                max_line_search_iters)
        calls += c
        s = alpha * d
        xn = x + s
        gn = grad(xn)
        y = gn - gx
        sy = jnp.vdot(s, y)
        # only positive-curvature pairs keep H positive-definite (Armijo
        # backtracking, unlike strong Wolfe, does not guarantee s.y > 0)
        if sy > 1e-12:
            rho = 1.0 / sy
            I = jnp.eye(n, dtype=x.dtype)
            V = I - rho * jnp.outer(s, y)
            H = V @ H @ V.T + rho * jnp.outer(s, s)
        fn_ = f(xn)
        calls += 1
        if jnp.abs(fn_ - fx) < tolerance_change:
            x, fx, gx = xn, fn_, gn
            converged = True
            break
        x, fx, gx = xn, fn_, gn
    wrap = (lambda v: Tensor(v)) if isinstance(initial_position, Tensor) \
        else (lambda v: v)
    return (Tensor(jnp.asarray(converged)), Tensor(jnp.asarray(calls)),
            wrap(x), wrap(jnp.asarray(fx)), wrap(gx), wrap(H))


def minimize_lbfgs(objective_func, initial_position, history_size=100,
                   max_iters=50, tolerance_grad=1e-7,
                   tolerance_change=1e-9, initial_inverse_hessian_estimate=
                   None, line_search_fn="strong_wolfe",
                   max_line_search_iters=50, initial_step_length=1.0,
                   dtype="float32", name=None):
    from ....core.tensor import Tensor

    f, x = _prep(objective_func, initial_position)
    grad = jax.grad(f)
    fx = f(x)
    gx = grad(x)
    calls = 1
    S, Y = [], []
    converged = False
    for _ in range(max_iters):
        if jnp.linalg.norm(gx, ord=jnp.inf) < tolerance_grad:
            converged = True
            break
        # two-loop recursion
        q = gx
        alphas = []
        for s, y in zip(reversed(S), reversed(Y)):
            rho = 1.0 / jnp.vdot(s, y)
            a = rho * jnp.vdot(s, q)
            alphas.append((a, rho, s, y))
            q = q - a * y
        gamma = (jnp.vdot(S[-1], Y[-1]) / jnp.vdot(Y[-1], Y[-1])
                 if S else 1.0)
        r = gamma * q
        for a, rho, s, y in reversed(alphas):
            b = rho * jnp.vdot(y, r)
            r = r + (a - b) * s
        d = -r
        alpha, c = _line_search(f, grad, x, d, fx, gx,
                                max_line_search_iters)
        calls += c
        s = alpha * d
        xn = x + s
        gn = grad(xn)
        y = gn - gx
        if jnp.vdot(s, y) > 1e-12:  # positive curvature only
            S.append(s)
            Y.append(y)
            if len(S) > history_size:
                S.pop(0)
                Y.pop(0)
        fn_ = f(xn)
        calls += 1
        if jnp.abs(fn_ - fx) < tolerance_change:
            x, fx, gx = xn, fn_, gn
            converged = True
            break
        x, fx, gx = xn, fn_, gn
    wrap = (lambda v: Tensor(v)) if isinstance(initial_position, Tensor) \
        else (lambda v: v)
    return (Tensor(jnp.asarray(converged)), Tensor(jnp.asarray(calls)),
            wrap(x), wrap(jnp.asarray(fx)), wrap(gx))
