"""paddle.incubate.optimizer parity — LookAhead, ModelAverage,
DistributedFusedLamb.

Reference: python/paddle/incubate/optimizer/ (lookahead.py, modelaverage.py,
distributed_fused_lamb.py). TPU note: "fused" distributed Lamb collapses to
the sharded Lamb step — gradients are already mesh-resident; the wrapper
keeps the API.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...optimizer import Lamb

__all__ = ["LookAhead", "ModelAverage", "DistributedFusedLamb"]


class LookAhead:
    """parity: incubate/optimizer/lookahead.py — k inner steps, then slow
    weights interpolate: slow += alpha * (fast - slow)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_num = 0
        self._slow = {}

    def __getattr__(self, name):
        return getattr(self.inner_optimizer, name)

    def step(self):
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k:
            return
        for p in self.inner_optimizer._parameter_list:
            pid = id(p)
            slow = self._slow.get(pid)
            if slow is None:
                slow = p._value
            slow = slow + self.alpha * (p._value - slow)
            self._slow[pid] = slow
            p._replace_value(slow)

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        return None, None


class ModelAverage:
    """parity: incubate/optimizer/modelaverage.py — maintains a running
    average of parameters; apply()/restore() swap it in and out."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._params = list(parameters or [])
        self._rate = average_window_rate
        self._sum = {id(p): jnp.zeros_like(p._value) for p in self._params}
        self._cnt = 0
        self._backup = {}

    def step(self):
        for p in self._params:
            self._sum[id(p)] = self._sum[id(p)] + p._value
        self._cnt += 1

    def apply(self, executor=None, need_restore=True):
        for p in self._params:
            self._backup[id(p)] = p._value
            p._replace_value(self._sum[id(p)] / max(self._cnt, 1))

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._replace_value(self._backup.pop(id(p)))


class DistributedFusedLamb(Lamb):
    """parity: incubate/optimizer/distributed_fused_lamb.py — on TPU the
    grads/moments live sharded on the mesh already, so this is Lamb with the
    fused-path constructor surface accepted."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 use_master_param_norm=True, gradient_accumulation_steps=1,
                 use_master_acc_grad=True, nproc_per_node=None, **kw):
        super().__init__(learning_rate=learning_rate,
                         lamb_weight_decay=lamb_weight_decay, beta1=beta1,
                         beta2=beta2, epsilon=epsilon, parameters=parameters,
                         grad_clip=grad_clip,
                         exclude_from_weight_decay_fn=exclude_from_weight_decay_fn)


# parity: incubate.optimizer.LBFGS (graduated to paddle.optimizer)
from ...optimizer.optimizers import LBFGS  # noqa: E402,F401

from . import functional  # noqa: E402,F401
