"""paddle.inference parity — the deployment predictor API.

Reference: paddle/fluid/inference/ AnalysisPredictor
(api/analysis_predictor.cc — Run :1574, ZeroCopyRun :2577) with its Config /
create_predictor Python surface (paddle.inference.Config/create_predictor).

TPU-native: a model saved by paddle_tpu.jit.save is serialized StableHLO +
weights. The predictor deserializes and AOT-executes it — XLA is both the
"analysis pass pipeline" and the "engine" (the TensorRT analogue is XLA AOT
compilation of the exported module). Zero-copy handles map to device arrays.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["Config", "Predictor", "create_predictor", "PlaceType"]


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"
    CUSTOM = "custom"


class Config:
    """parity: paddle.inference.Config (model path + runtime knobs; the
    GPU/TensorRT toggles are accepted and mapped to XLA equivalents or
    no-ops, recorded for introspection)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # paddle convention: prog_file may be the base path of jit.save
        self.model_path = prog_file
        self.params_path = params_file
        self._device = "tpu" if any(
            d.platform == "tpu" for d in jax.devices()) else "cpu"
        self._memory_pool_mb = 0
        self._flags: Dict[str, object] = {}

    def set_model(self, prog_file, params_file=None):
        self.model_path = prog_file
        self.params_path = params_file

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._memory_pool_mb = memory_pool_init_size_mb

    def disable_gpu(self):
        self._device = "cpu"

    def enable_memory_optim(self, x=True):
        self._flags["memory_optim"] = x

    def switch_ir_optim(self, x=True):
        self._flags["ir_optim"] = x  # XLA always optimizes; recorded only

    def set_cpu_math_library_num_threads(self, n):
        self._flags["cpu_threads"] = n

    def device(self):
        return self._device


class _IOHandle:
    """Zero-copy tensor handle (parity: ZeroCopyTensor)."""

    def __init__(self, predictor, name):
        self._p = predictor
        self.name = name

    def copy_from_cpu(self, arr: np.ndarray):
        self._p._inputs[self.name] = jnp.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._p._outputs[self.name])

    def shape(self):
        src = self._p._inputs if self.name in self._p._inputs \
            else self._p._outputs
        return list(src[self.name].shape)


class Predictor:
    """parity: AnalysisPredictor through the paddle.inference API shape."""

    def __init__(self, config: Config):
        from .. import jit as _jit

        if config.model_path is None:
            raise ValueError("Config.model_path is required")
        self._layer = _jit.load(config.model_path)
        self._n_inputs = getattr(self._layer, "num_inputs", None)
        self._inputs: Dict[str, jax.Array] = {}
        self._outputs: Dict[str, jax.Array] = {}
        self._input_names: List[str] = []
        n = self._layer._exported.in_avals
        # first two avals trees are params/buffers; inputs follow
        self._input_names = [f"x{i}" for i in range(
            max(0, len(self._layer._exported.in_avals) - 2))]

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_output_names(self) -> List[str]:
        return [f"out{i}" for i in range(len(self._outputs))] or ["out0"]

    def get_input_handle(self, name) -> _IOHandle:
        return _IOHandle(self, name)

    def get_output_handle(self, name) -> _IOHandle:
        return _IOHandle(self, name)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        if inputs is not None:
            for i, a in enumerate(inputs):
                self._inputs[f"x{i}"] = jnp.asarray(a)
        args = [self._inputs[n] for n in self._input_names
                if n in self._inputs]
        outs = self._layer(*args)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        self._outputs = {f"out{i}": (o._value if isinstance(o, Tensor) else o)
                         for i, o in enumerate(outs)}
        if inputs is not None:
            return [np.asarray(v) for v in self._outputs.values()]
        return True


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


class DataType:
    """parity: paddle_infer.DataType enum."""
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6
    FLOAT64 = 7
    BOOL = 8


class PrecisionType:
    """parity: paddle_infer.PrecisionType enum (TRT precision knob; on TPU
    the analogue is the XLA compile dtype)."""
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


class XpuConfig:
    """parity: paddle_infer.XpuConfig — accepted for config compat; no XPU
    in this build."""

    def __init__(self):
        self.device_id = 0
        self.l3_size = 0


class PredictorPool:
    """parity: paddle_infer.PredictorPool — N predictor handles over ONE
    loaded program (the model deserializes once; XLA executables are
    thread-safe, so handles share the compiled artifact)."""

    def __init__(self, config, size=1):
        first = create_predictor(config)
        self._predictors = [first]
        for _ in range(int(size) - 1):
            clone = Predictor.__new__(Predictor)
            clone.__dict__.update(first.__dict__)
            # handles must be per-predictor: fresh IO state so concurrent
            # retrieve() users don't clobber each other (the loaded layer
            # itself stays shared)
            clone._inputs = {}
            clone._outputs = {}
            self._predictors.append(clone)

    def retrieve(self, idx):
        return self._predictors[idx]


def get_version():
    from .. import __version__

    return __version__


def get_trt_compile_version():
    """No TensorRT on TPU — the XLA AOT path replaces it."""
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


def get_num_bytes_of_data_type(dtype):
    sizes = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
             DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
             DataType.BFLOAT16: 2, DataType.FLOAT64: 8, DataType.BOOL: 1}
    return sizes.get(dtype, 4)


def _get_phi_kernel_name(op_name):
    """parity shim: kernel naming is an XLA concern here; identity map."""
    return op_name


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision=None,
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    """parity: inference convert_to_mixed_precision — the reference rewrites
    a saved program to fp16/bf16. StableHLO exports here stay dtype-typed;
    re-export the model with amp.auto_cast (documented path)."""
    raise NotImplementedError(
        "convert_to_mixed_precision: re-export the model under "
        "paddle_tpu.amp.auto_cast(dtype='bfloat16') + jit.save — StableHLO "
        "artifacts carry their dtypes (XLA is the precision rewrite layer)")


__all__ += ["DataType", "PrecisionType", "XpuConfig", "PredictorPool",
            "get_version", "get_trt_compile_version",
            "get_trt_runtime_version", "get_num_bytes_of_data_type",
            "convert_to_mixed_precision", "_get_phi_kernel_name"]
