"""paddle.device.xpu (parity: python/paddle/device/xpu/) — no XPU in this
build; synchronize defers to the generic device barrier."""
from .. import synchronize  # noqa: F401
from .._memory import empty_cache  # noqa: F401

__all__ = ["synchronize", "empty_cache"]
