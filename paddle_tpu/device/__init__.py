"""Device / Place layer.

TPU-native equivalent of the reference's Place/Backend machinery
(reference: paddle/phi/common/place.h:31-39, python/paddle/device/__init__.py:284
set_device). Here 'tpu' is the first-class backend; 'cpu' always exists; any
platform jax exposes (gpu, axon, ...) is addressable through the same API.
"""
from __future__ import annotations

import threading
from typing import Optional

import jax

__all__ = [
    "Place", "CPUPlace", "TPUPlace", "CustomPlace",
    "set_device", "get_device", "get_all_devices", "device_count",
    "is_compiled_with_tpu", "jax_device", "current_jax_device",
    "synchronize",
]


class Place:
    """A (device_type, device_id) pair, resolvable to a concrete jax.Device."""

    __slots__ = ("device_type", "device_id")

    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = device_type
        self.device_id = device_id

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def jax_device(self) -> Optional[jax.Device]:
        return _resolve_jax_device(self.device_type, self.device_id)

    def is_cpu_place(self):
        return self.device_type == "cpu"

    def is_tpu_place(self):
        return self.device_type == "tpu"


def CPUPlace(idx: int = 0) -> Place:
    return Place("cpu", idx)


def TPUPlace(idx: int = 0) -> Place:
    return Place("tpu", idx)


def CustomPlace(device_type: str, idx: int = 0) -> Place:
    """Counterpart of the reference's pluggable CustomPlace
    (paddle/phi/common/place.h:41 CustomRegisteredDeviceMap)."""
    return Place(device_type, idx)


_TPU_LIKE = ("tpu", "axon")  # axon = tunneled TPU platform name in this environment


def _platform_of(dev: jax.Device) -> str:
    p = dev.platform.lower()
    return "tpu" if p in _TPU_LIKE else p


def _resolve_jax_device(device_type: str, device_id: int) -> Optional[jax.Device]:
    for d in jax.devices():
        if _platform_of(d) == device_type and d.id == device_id:
            return d
    # fall back to local index within the platform
    same = [d for d in jax.devices() if _platform_of(d) == device_type]
    if same and device_id < len(same):
        return same[device_id]
    if device_type == "cpu":
        try:
            return jax.devices("cpu")[device_id]
        except RuntimeError:
            return None
    return None


_state = threading.local()


def _default_place() -> Place:
    try:
        d = jax.devices()[0]
    except RuntimeError:
        return CPUPlace()
    return Place(_platform_of(d), d.id)


def set_device(device: str) -> Place:
    """paddle.device.set_device parity: 'tpu', 'tpu:0', 'cpu', ..."""
    if ":" in device:
        kind, _, idx = device.partition(":")
        place = Place(kind, int(idx))
    else:
        place = Place(device, 0)
    if place.jax_device() is None:
        raise ValueError(
            f"device '{device}' not available; visible platforms: "
            f"{sorted({_platform_of(d) for d in jax.devices()})}"
        )
    _state.place = place
    return place


def get_device() -> str:
    place = getattr(_state, "place", None) or _default_place()
    return f"{place.device_type}:{place.device_id}"


def current_place() -> Place:
    place = getattr(_state, "place", None)
    if place is None:
        place = _default_place()
        _state.place = place
    return place


def current_jax_device() -> Optional[jax.Device]:
    return current_place().jax_device()


def jax_device(place=None) -> Optional[jax.Device]:
    if place is None:
        return current_jax_device()
    if isinstance(place, str):
        kind, _, idx = place.partition(":")
        place = Place(kind, int(idx or 0))
    return place.jax_device()


def get_all_devices():
    return [f"{_platform_of(d)}:{d.id}" for d in jax.devices()]


def device_count(device_type: Optional[str] = None) -> int:
    if device_type is None:
        return len(jax.devices())
    return sum(1 for d in jax.devices() if _platform_of(d) == device_type)


def is_compiled_with_tpu() -> bool:
    return any(_platform_of(d) == "tpu" for d in jax.devices())


def synchronize(device=None):
    """Block until all outstanding device work completes
    (counterpart of paddle.device.synchronize)."""
    jax.effects_barrier()


def place_of_array(arr) -> Place:
    try:
        dev = list(arr.devices())[0]
        return Place(_platform_of(dev), dev.id)
    except Exception:
        return CPUPlace()


# -- streams & events -------------------------------------------------------
# parity: paddle.device.Stream/Event + stream_guard (python/paddle/device/
# __init__.py, device/cuda/streams.py). XLA owns real stream scheduling on
# TPU (one compute stream + DMA; the latency-hiding scheduler interleaves
# collectives), so these objects provide ORDERING semantics only: record/
# wait/synchronize map to effects barriers, and the "current stream" is a
# thread-local tag user code can branch on.

import threading as _threading
import time as _time


class Event:
    """parity: paddle.device.Event — records a point in the issue order."""

    def __init__(self, device=None, enable_timing=False, blocking=False,
                 interprocess=False):
        self._recorded = None
        self._enable_timing = enable_timing

    def record(self, stream=None):
        jax.effects_barrier()
        self._recorded = _time.perf_counter()

    def query(self) -> bool:
        return self._recorded is not None

    def synchronize(self):
        jax.effects_barrier()

    def elapsed_time(self, end_event) -> float:
        if self._recorded is None or end_event._recorded is None:
            raise RuntimeError("both events must be recorded")
        return (end_event._recorded - self._recorded) * 1000.0


class Stream:
    """parity: paddle.device.Stream — on TPU all work issues onto XLA's
    stream; wait_event/wait_stream/synchronize provide the ordering API."""

    def __init__(self, device=None, priority=2, blocking=False):
        self.device = device

    def wait_event(self, event: "Event"):
        event.synchronize()

    def wait_stream(self, stream: "Stream"):
        jax.effects_barrier()

    def record_event(self, event: "Event" = None) -> "Event":
        ev = event or Event()
        ev.record(self)
        return ev

    def synchronize(self):
        jax.effects_barrier()

    def query(self) -> bool:
        return True


_stream_tls = _threading.local()


def current_stream(device=None) -> Stream:
    cur = getattr(_stream_tls, "stream", None)
    if cur is None:
        cur = Stream(device)
        _stream_tls.stream = cur
    return cur


def set_stream(stream: Stream) -> Stream:
    prev = current_stream()
    _stream_tls.stream = stream
    return prev


class stream_guard:
    """parity: paddle.device.stream_guard context manager."""

    def __init__(self, stream: Stream):
        self._stream = stream
        self._prev = None

    def __enter__(self):
        self._prev = set_stream(self._stream)
        return self._stream

    def __exit__(self, *exc):
        set_stream(self._prev)


class CUDAPlace(Place):
    """parity: paddle.CUDAPlace. This build targets TPU (CUDA disabled), so
    construction raises — matching the reference in a non-CUDA build
    (phi/common/place.h + is_compiled_with_cuda() checks) — while remaining
    a class so ``isinstance(place, paddle.CUDAPlace)`` works in ported
    code."""

    def __init__(self, idx: int = 0):
        raise RuntimeError(
            "CUDAPlace is unavailable: paddle_tpu is not compiled with "
            "CUDA. Use TPUPlace()/CPUPlace() instead.")


class CUDAPinnedPlace(Place):
    """parity: paddle.CUDAPinnedPlace (unavailable in a non-CUDA build)."""

    def __init__(self):
        raise RuntimeError(
            "CUDAPinnedPlace is unavailable: paddle_tpu is not compiled "
            "with CUDA.")


class XPUPlace(Place):
    """parity: paddle.XPUPlace (unavailable: no XPU in this build)."""

    def __init__(self, idx: int = 0):
        raise RuntimeError(
            "XPUPlace is unavailable: paddle_tpu is not compiled with XPU.")


class IPUPlace(Place):
    """parity: paddle.device.IPUPlace (unavailable: no IPU in this build)."""

    def __init__(self):
        raise RuntimeError(
            "IPUPlace is unavailable: paddle_tpu is not compiled with IPU.")


def get_all_device_type():
    """parity: device.get_all_device_type — device types visible to the
    runtime."""
    return sorted({_platform_of(d) for d in jax.devices()} | {"cpu"})


def get_all_custom_device_type():
    return [t for t in get_all_device_type() if t not in ("cpu", "gpu")]


def get_available_device():
    return [f"{_platform_of(d)}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [s for s in get_available_device()
            if not s.startswith(("cpu", "gpu"))]


def get_cudnn_version():
    """parity: device.get_cudnn_version — None when CUDA is unavailable."""
    return None


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    return False


def is_compiled_with_distribute():
    return True


def is_compiled_with_custom_device(device_type: str) -> bool:
    """TPU rides the PJRT plugin mechanism — report it as the available
    custom device type."""
    return device_type in get_all_device_type()


from ._memory import (  # noqa: E402,F401
    empty_cache, max_memory_allocated, max_memory_reserved,
    memory_allocated, memory_reserved, reset_max_memory_allocated,
    reset_max_memory_reserved,
)
from . import cuda  # noqa: E402,F401
from . import xpu  # noqa: E402,F401
