"""Device memory statistics over PJRT (parity: the reference's
memory/stats.cc registry behind paddle.device.cuda.max_memory_allocated)."""
from __future__ import annotations

import jax


def _resolve(device, device_id):
    """Paddle signature puts the device first: accept an int index, a
    'xpu:N'-style string, a Place, or None (falls back to device_id)."""
    if device is None:
        return device_id if isinstance(device_id, int) else 0
    if isinstance(device, int):
        return device
    if isinstance(device, str) and ":" in device:
        return int(device.rsplit(":", 1)[1])
    idx = getattr(device, "device_id", None)
    return idx if isinstance(idx, int) else 0


def _stats(device=None, device_id=0):
    try:
        dev = jax.devices()[_resolve(device, device_id)]
        return dev.memory_stats() or {}
    except Exception:
        return {}


def memory_allocated(device=None, device_id=0):
    return int(_stats(device, device_id).get("bytes_in_use", 0))


def max_memory_allocated(device=None, device_id=0):
    s = _stats(device, device_id)
    return int(s.get("peak_bytes_in_use", s.get("bytes_in_use", 0)))


def memory_reserved(device=None, device_id=0):
    s = _stats(device, device_id)
    return int(s.get("bytes_reserved", s.get("bytes_in_use", 0)))


def max_memory_reserved(device=None, device_id=0):
    s = _stats(device, device_id)
    return int(s.get("peak_bytes_reserved",
                     s.get("peak_bytes_in_use", 0)))


def reset_max_memory_allocated(device=None):
    """PJRT exposes cumulative peaks only; reset is a no-op recorded for
    API compat."""


def reset_max_memory_reserved(device=None):
    pass


def empty_cache():
    """Ask XLA to release cached buffers (best-effort)."""
    import gc

    gc.collect()
    try:
        jax.clear_caches()
    except Exception:
        pass
