"""Device memory statistics over PJRT (parity: the reference's
memory/stats.cc registry behind paddle.device.cuda.max_memory_allocated)."""
from __future__ import annotations

import jax


def _stats(device_id=0):
    try:
        dev = jax.devices()[device_id if isinstance(device_id, int) else 0]
        return dev.memory_stats() or {}
    except Exception:
        return {}


def memory_allocated(device=None, device_id=0):
    return int(_stats(device_id).get("bytes_in_use", 0))


def max_memory_allocated(device=None, device_id=0):
    s = _stats(device_id)
    return int(s.get("peak_bytes_in_use", s.get("bytes_in_use", 0)))


def memory_reserved(device=None, device_id=0):
    s = _stats(device_id)
    return int(s.get("bytes_reserved", s.get("bytes_in_use", 0)))


def max_memory_reserved(device=None, device_id=0):
    s = _stats(device_id)
    return int(s.get("peak_bytes_reserved",
                     s.get("peak_bytes_in_use", 0)))


def reset_max_memory_allocated(device=None):
    """PJRT exposes cumulative peaks only; reset is a no-op recorded for
    API compat."""


def reset_max_memory_reserved(device=None):
    pass


def empty_cache():
    """Ask XLA to release cached buffers (best-effort)."""
    import gc

    gc.collect()
    try:
        jax.clear_caches()
    except Exception:
        pass
