"""paddle.device.cuda (parity: python/paddle/device/cuda/__init__.py).

No CUDA in a TPU build — the Stream/Event/stream_guard ordering API and the
memory statistics are the device-generic ones (they operate on whatever
device jax exposes, which is how ported `paddle.device.cuda.*` telemetry
code keeps working); device_count() reports 0 CUDA devices.
"""
from .._memory import (  # noqa: F401
    empty_cache, max_memory_allocated, max_memory_reserved,
    memory_allocated, memory_reserved, reset_max_memory_allocated,
    reset_max_memory_reserved,
)
from .. import Event, Stream, current_stream, stream_guard, synchronize  # noqa: F401

__all__ = ["Stream", "Event", "current_stream", "synchronize",
           "device_count", "empty_cache", "max_memory_allocated",
           "max_memory_reserved", "memory_allocated", "memory_reserved",
           "stream_guard", "get_device_properties", "get_device_name",
           "get_device_capability", "reset_max_memory_allocated",
           "reset_max_memory_reserved"]


def device_count():
    """Number of CUDA devices — 0 in a TPU build."""
    return 0


def get_device_properties(device=None):
    raise RuntimeError(
        "get_device_properties: paddle_tpu is not compiled with CUDA; "
        "query TPU devices via jax.devices()")


def get_device_name(device=None):
    import jax

    devs = jax.devices()
    return devs[0].device_kind if devs else "cpu"


def get_device_capability(device=None):
    raise RuntimeError(
        "get_device_capability: no CUDA SM capability on TPU")
