"""paddle.fft parity (reference: python/paddle/fft.py over pocketfft-backed
kernels). TPU-native: jnp.fft lowers to XLA FFT HLO directly."""
from __future__ import annotations

import jax.numpy as jnp

from .core.tensor import Tensor
from .ops.dispatch import apply
from .ops.creation import _t

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2",
    "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn", "fftfreq",
    "rfftfreq", "fftshift", "ifftshift",
]


def _wrap1(name, jfn):
    def op(x, n=None, axis=-1, norm="backward", name_=None):
        return apply(name, lambda v: jfn(v, n=n, axis=axis, norm=norm), _t(x))
    op.__name__ = name
    return op


def _wrapn(name, jfn, s_name="s"):
    def op(x, s=None, axes=None, norm="backward", name_=None):
        return apply(name, lambda v: jfn(v, s=s, axes=axes, norm=norm), _t(x))
    op.__name__ = name
    return op


fft = _wrap1("fft", jnp.fft.fft)
ifft = _wrap1("ifft", jnp.fft.ifft)
rfft = _wrap1("rfft", jnp.fft.rfft)
irfft = _wrap1("irfft", jnp.fft.irfft)
hfft = _wrap1("hfft", jnp.fft.hfft)
ihfft = _wrap1("ihfft", jnp.fft.ihfft)
fftn = _wrapn("fftn", jnp.fft.fftn)
ifftn = _wrapn("ifftn", jnp.fft.ifftn)
rfftn = _wrapn("rfftn", jnp.fft.rfftn)
irfftn = _wrapn("irfftn", jnp.fft.irfftn)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply("fft2", lambda v: jnp.fft.fft2(v, s=s, axes=axes, norm=norm), _t(x))


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply("ifft2", lambda v: jnp.fft.ifft2(v, s=s, axes=axes, norm=norm), _t(x))


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply("rfft2", lambda v: jnp.fft.rfft2(v, s=s, axes=axes, norm=norm), _t(x))


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply("irfft2", lambda v: jnp.fft.irfft2(v, s=s, axes=axes, norm=norm), _t(x))


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d))


def fftshift(x, axes=None, name=None):
    return apply("fftshift", lambda v: jnp.fft.fftshift(v, axes=axes), _t(x))


def ifftshift(x, axes=None, name=None):
    return apply("ifftshift", lambda v: jnp.fft.ifftshift(v, axes=axes), _t(x))
