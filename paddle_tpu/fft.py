"""paddle.fft parity (reference: python/paddle/fft.py over pocketfft-backed
kernels). TPU-native: jnp.fft lowers to XLA FFT HLO directly."""
from __future__ import annotations

import jax.numpy as jnp

import numpy as np

from .core.tensor import Tensor
from .ops.dispatch import apply
from .ops.creation import _t

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2",
    "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn", "fftfreq",
    "rfftfreq", "fftshift", "ifftshift",
]


def _wrap1(name, jfn):
    def op(x, n=None, axis=-1, norm="backward", name_=None):
        return apply(name, lambda v: jfn(v, n=n, axis=axis, norm=norm), _t(x))
    op.__name__ = name
    return op


def _wrapn(name, jfn, s_name="s"):
    def op(x, s=None, axes=None, norm="backward", name_=None):
        return apply(name, lambda v: jfn(v, s=s, axes=axes, norm=norm), _t(x))
    op.__name__ = name
    return op


fft = _wrap1("fft", jnp.fft.fft)
ifft = _wrap1("ifft", jnp.fft.ifft)
rfft = _wrap1("rfft", jnp.fft.rfft)
irfft = _wrap1("irfft", jnp.fft.irfft)
hfft = _wrap1("hfft", jnp.fft.hfft)
ihfft = _wrap1("ihfft", jnp.fft.ihfft)
fftn = _wrapn("fftn", jnp.fft.fftn)
ifftn = _wrapn("ifftn", jnp.fft.ifftn)
rfftn = _wrapn("rfftn", jnp.fft.rfftn)
irfftn = _wrapn("irfftn", jnp.fft.irfftn)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply("fft2", lambda v: jnp.fft.fft2(v, s=s, axes=axes, norm=norm), _t(x))


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply("ifft2", lambda v: jnp.fft.ifft2(v, s=s, axes=axes, norm=norm), _t(x))


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply("rfft2", lambda v: jnp.fft.rfft2(v, s=s, axes=axes, norm=norm), _t(x))


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply("irfft2", lambda v: jnp.fft.irfft2(v, s=s, axes=axes, norm=norm), _t(x))


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d))


def fftshift(x, axes=None, name=None):
    return apply("fftshift", lambda v: jnp.fft.fftshift(v, axes=axes), _t(x))


def ifftshift(x, axes=None, name=None):
    return apply("ifftshift", lambda v: jnp.fft.ifftshift(v, axes=axes), _t(x))


def _hfft_shape(v_shape, s, axes):
    axes = [a % len(v_shape) for a in axes]
    if s is not None:
        return list(s), axes
    out = [v_shape[a] for a in axes]
    out[-1] = 2 * (v_shape[axes[-1]] - 1)
    return out, axes


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """parity: fft.py hfft2 — FFT of a signal Hermitian-symmetric in the
    last transform axis; real output. Identity (verified vs torch):
    hfftn(x, s) = irfftn(conj(x), s) * prod(s)."""
    return hfftn(x, s=s, axes=axes, norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=axes, norm=norm)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    t = _t(x)
    ax = list(axes) if axes is not None else list(range(t.ndim))

    def fn(v):
        out_s, axl = _hfft_shape(v.shape, s, ax)
        scale = 1.0
        if norm == "backward":
            scale = float(np.prod(out_s))
        elif norm == "ortho":
            scale = float(np.sqrt(np.prod(out_s)))
        return jnp.fft.irfftn(jnp.conj(v), s=out_s, axes=axl) * scale

    return apply("hfftn", fn, t)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    t = _t(x)
    ax = list(axes) if axes is not None else list(range(t.ndim))

    def fn(v):
        axl = [a % v.ndim for a in ax]
        sl = list(s) if s is not None else [v.shape[a] for a in axl]
        scale = 1.0
        if norm == "backward":
            scale = float(np.prod(sl))
        elif norm == "ortho":
            scale = float(np.sqrt(np.prod(sl)))
        return jnp.conj(jnp.fft.rfftn(v, s=sl, axes=axl)) / scale

    return apply("ihfftn", fn, t)


__all__ += ["hfft2", "ihfft2", "hfftn", "ihfftn"]
