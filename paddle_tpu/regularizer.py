"""paddle.regularizer (parity: python/paddle/regularizer.py)."""
from .optimizer import L1Decay, L2Decay  # noqa: F401


class WeightDecayRegularizer:
    """Base interface of weight-decay regularizers."""

    def __call__(self, param, grad, block=None):
        raise NotImplementedError


__all__ = ["L1Decay", "L2Decay"]
