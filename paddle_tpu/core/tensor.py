"""The framework Tensor.

TPU-native re-design of the reference's eager Tensor
(reference: paddle/phi/api/include/tensor.h:82 paddle::Tensor;
pybind surface paddle/fluid/pybind/eager_method.cc — numpy() :154,
_copy_to :613, eager_properties.cc for .grad/.shape/.place/.dtype).

A Tensor wraps an immutable jax.Array. "In-place" mutation is a buffer swap
(the old array stays alive for any autograd residuals that captured it), with a
version counter kept for API parity. Autograd state lives directly on the
tensor: ``_grad_node``/``_output_index`` point into the tape
(see autograd/tape.py), leaves own an AccumulateGrad and a ``.grad``.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..device import Place, current_jax_device, place_of_array
from ..framework import dtype as dtypes

# populated by jit.branch_capture while a branch oracle is active (kept here
# as a plain list so the core layer never imports jit); each entry is a
# callable(value) -> Optional[bool]
_branch_oracle_hook = []


class Tensor:
    __slots__ = (
        "_value", "stop_gradient", "_grad", "_grad_node", "_output_index",
        "_accumulate_node", "name", "persistable", "_version", "__weakref__",
        "is_parameter", "_trainable_attrs", "_dist_attr",
    )

    def __init__(self, value, stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(value, Tensor):
            value = value._value
        if getattr(value, "_is_segment_lazy", False):
            # aliasing a segment-deferred value: register as an owner so
            # the flush binds the computed array here too (jit/segments)
            from ..jit.segments import note_lazy_ref

            note_lazy_ref(value, self)
        elif not isinstance(value, jax.Array) and not isinstance(
                value, jax.core.Tracer):
            value = jnp.asarray(value)
        self._value = value
        self.stop_gradient = stop_gradient
        self._grad = None
        self._grad_node = None
        self._output_index = 0
        self._accumulate_node = None
        self.name = name
        self.persistable = False
        self.is_parameter = False
        self._version = 0

    # -- basic properties ------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    def numel(self):
        return self.size

    @property
    def dtype(self) -> dtypes.DType:
        return dtypes.convert_dtype(np.dtype(self._value.dtype))

    @property
    def place(self) -> Place:
        return place_of_array(self._value)

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    @property
    def grad(self) -> Optional["Tensor"]:
        return self._grad

    @grad.setter
    def grad(self, g):
        if g is not None and not isinstance(g, Tensor):
            g = Tensor(g)
        self._grad = g

    @property
    def T(self):
        from .. import ops as _ops

        perm = list(range(self.ndim))[::-1]
        return _ops.transpose(self, perm)

    def t(self):
        return self.T

    @property
    def mT(self):
        from .. import ops as _ops

        perm = list(range(self.ndim))
        perm[-2], perm[-1] = perm[-1], perm[-2]
        return _ops.transpose(self, perm)

    # -- host interop ----------------------------------------------------
    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        return self._value.item(*args)

    def tolist(self):
        return self.numpy().tolist()

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __repr__(self):
        sg = self.stop_gradient
        try:
            data = np.asarray(self._value)
            body = np.array2string(data, precision=6, separator=", ")
        except Exception:
            body = f"<traced {self._value}>"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
            f"place={self.place}, stop_gradient={sg},\n       {body})"
        )

    def __bool__(self):
        # under jit branch capture, a traced scalar condition becomes a
        # lax.cond decision point instead of a ConcretizationTypeError;
        # the hook list is registered by jit.branch_capture only while an
        # oracle is active, so eager `if tensor:` stays one empty-list check
        if _branch_oracle_hook:
            decided = _branch_oracle_hook[-1](self._value)
            if decided is not None:
                return decided
        return bool(self._value)

    def __int__(self):
        return int(self._value)

    def __float__(self):
        return float(self._value)

    def __index__(self):
        return int(self._value)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return object.__format__(self, spec)

    # -- mutation ---------------------------------------------------------
    def _replace_value(self, new_value):
        """In-place buffer swap (reference inplace kernels; here immutable
        arrays make residual corruption impossible)."""
        if not isinstance(new_value, (jax.Array, jax.core.Tracer)):
            new_value = jnp.asarray(new_value)
        self._value = new_value
        self._version += 1
        return self

    def _adopt(self, result: "Tensor"):
        """Adopt another tensor's value and autograd position (used by the
        in-place op variants: the reference's inplace kernels + version
        counting, here expressed as out-of-place + identity rebind)."""
        if getattr(result._value, "_is_segment_lazy", False):
            from ..jit.segments import note_lazy_ref

            note_lazy_ref(result._value, self)
        self._value = result._value
        self._grad_node = result._grad_node
        self._output_index = result._output_index
        self.stop_gradient = result.stop_gradient
        self._version += 1
        return self

    def _accumulate_grad(self, cot):
        if isinstance(cot, Tensor):
            cot = cot._value
        if self._grad is None:
            self._grad = Tensor(cot, stop_gradient=True)
        else:
            self._grad = Tensor(self._grad._value + cot, stop_gradient=True)

    def clear_grad(self, set_to_zero: bool = False):
        if set_to_zero and self._grad is not None:
            self._grad = Tensor(jnp.zeros_like(self._grad._value))
        else:
            self._grad = None

    clear_gradient = clear_grad

    def zero_(self):
        return self._replace_value(jnp.zeros_like(self._value))

    def fill_(self, value):
        return self._replace_value(jnp.full_like(self._value, value))

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        value = jnp.asarray(value, dtype=self._value.dtype)
        if tuple(value.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {value.shape} vs {self._value.shape}"
            )
        return self._replace_value(value)

    def copy_(self, other, non_blocking=False):
        return self.set_value(other)

    # -- autograd ----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        from ..autograd import backward as _backward

        _backward([self], [grad_tensor] if grad_tensor is not None else None,
                  retain_graph=retain_graph)

    def register_hook(self, hook):
        from ..autograd.tape import RemovableHandle
        from ..ops.dispatch import _edge_for

        if self.stop_gradient:
            raise RuntimeError("cannot register hook on a stop_gradient tensor")
        if self._grad_node is not None:
            hooks = self._grad_node.output_hooks.setdefault(self._output_index, {})
        else:
            target, _ = _edge_for(self)
            hooks = target.hooks
        h = RemovableHandle(hooks)
        hooks[h.id] = hook
        return h

    def retain_grads(self):
        if self._grad_node is None:
            return
        import weakref

        ref = weakref.ref(self)

        def _save(g):
            t = ref()
            if t is not None:
                t._accumulate_grad(g._value)
            return None

        self.register_hook(_save)

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from .. import ops as _ops

        return _ops.assign(self)

    # -- device movement ---------------------------------------------------
    def _copy_to(self, place, blocking: bool = True) -> "Tensor":
        from ..device import jax_device

        dev = jax_device(place) if not hasattr(place, "jax_device") else place.jax_device()
        return Tensor(jax.device_put(self._value, dev), stop_gradient=self.stop_gradient)

    def cpu(self):
        return self._copy_to("cpu:0")

    def tpu(self, idx: int = 0):
        return self._copy_to(f"tpu:{idx}")

    def to(self, *args, **kwargs):
        # accepts dtype-like or device-like (paddle Tensor.to parity)
        out = self
        for key, a in list(zip([None] * len(args), args)) + list(kwargs.items()):
            if a is None or isinstance(a, bool) or key == "blocking":
                continue
            try:
                d = dtypes.convert_dtype(a)
                out = out.astype(d)
                continue
            except (ValueError, TypeError):
                pass
            if isinstance(a, (str, Place)):
                out = out._copy_to(a)
        return out

    def pin_memory(self):
        return self

    def cuda(self, *a, **k):
        raise RuntimeError("paddle_tpu is a TPU-native framework; CUDA is not available")

    # -- dtype -------------------------------------------------------------
    def astype(self, dtype) -> "Tensor":
        from .. import ops as _ops

        return _ops.cast(self, dtype)

    def cast(self, dtype):
        return self.astype(dtype)

    # -- misc helpers used everywhere --------------------------------------
    def apply(self, func):
        return func(self)

    def element_size(self):
        return self.dtype.itemsize

    def get_tensor(self):
        return self

    def value(self):
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True


def _tensor_flatten(t: Tensor):
    return (t._value,), (t.stop_gradient,)


def _tensor_unflatten(aux, children):
    return Tensor(children[0], stop_gradient=aux[0])


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


class Parameter(Tensor):
    """Trainable tensor (reference: python/paddle/base/framework.py
    EagerParamBase); stop_gradient defaults to False."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip")

    def __init__(self, value, trainable: bool = True, name: Optional[str] = None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.is_parameter = True
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True


jax.tree_util.register_pytree_node(
    Parameter,
    lambda p: ((p._value,), (p.stop_gradient,)),
    lambda aux, ch: Parameter(ch[0], trainable=not aux[0]),
)
