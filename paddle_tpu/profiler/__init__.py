"""paddle_tpu.profiler.

Parity: python/paddle/profiler/ (Profiler — profiler.py:358, scheduler states
:89, export_chrome_tracing :227, RecordEvent, timer). TPU-native backing:
jax.profiler traces (XPlane → TensorBoard/Perfetto) replace the reference's
host tracer + CUPTI pipeline (paddle/fluid/platform/profiler/).
"""
from __future__ import annotations

import contextlib
import os
import time
from enum import Enum
from typing import Callable, Iterable, Optional

import jax

from ..observability import state as _obs_state
from ..observability.tracing import get_tracer as _get_tracer
from .statistics import Benchmark, EventLedger, SortedKeys, build_summary

# stack of active profilers: RecordEvent feeds the innermost; a nested
# profiler's stop() restores the outer one
_ACTIVE: list = []


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(closed: int = 0, ready: int = 0, record: int = 1,
                   repeat: int = 0, skip_first: int = 0) -> Callable[[int], ProfilerState]:
    period = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= period * repeat:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """Returns an on_trace_ready handler that writes the host-side event
    ledger as a chrome://tracing JSON next to the jax XPlane dump
    (parity: profiler.export_chrome_tracing — profiler.py:227)."""
    def handler(prof):
        import json
        import os as _os

        _os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"worker_{_os.getpid()}"
        events = [
            {"name": n, "ph": "X", "pid": 0, "tid": 0,
             "ts": t0 * 1e6, "dur": (t1 - t0) * 1e6,
             "cat": "host"}
            for n, t0, t1 in prof._ledger.spans]
        path = _os.path.join(dir_name, f"{name}.pt.trace.json")
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)
        print(f"[profiler] chrome trace: {path} "
              f"(device XPlane under {dir_name})")

    handler._dir = dir_name
    return handler


class Profiler:
    """parity: paddle.profiler.Profiler (start/stop/step, scheduler)."""

    def __init__(self, targets: Optional[Iterable] = None, scheduler=None,
                 on_trace_ready=None, record_shapes=False, profile_memory=False,
                 timer_only=False, with_flops=False):
        if callable(scheduler):
            self._scheduler = scheduler
        elif isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            self._scheduler = make_scheduler(closed=start, ready=0,
                                             record=end - start, skip_first=0)
        else:
            self._scheduler = None
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._dir = getattr(on_trace_ready, "_dir", None) or "./profiler_log"
        self._step = 0
        self._active = False
        self._step_times = []
        self._t_last = None
        self._ledger = EventLedger()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def start(self):
        if self not in _ACTIVE:
            _ACTIVE.append(self)
        self._t_last = time.time()
        if self._timer_only:
            return
        state = self._scheduler(self._step) if self._scheduler else \
            ProfilerState.RECORD
        if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._begin_trace()

    def _begin_trace(self):
        if not self._active:
            os.makedirs(self._dir, exist_ok=True)
            try:
                jax.profiler.start_trace(self._dir)
                self._active = True
            except Exception:
                self._active = False

    def _end_trace(self):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            if self._on_trace_ready is not None:
                self._on_trace_ready(self)

    def step(self, num_samples: Optional[int] = None):
        now = time.time()
        if self._t_last is not None:
            self._step_times.append((now - self._t_last, num_samples))
        self._t_last = now
        self._step += 1
        if self._timer_only or self._scheduler is None:
            return
        state = self._scheduler(self._step)
        if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._begin_trace()
        else:
            self._end_trace()

    def stop(self):
        try:
            self._end_trace()
        finally:
            # Exception-safe stack restore: drop self AND any nested
            # profiler that leaked above it (a body that raised between an
            # inner start() and its stop() would otherwise leave the inner
            # profiler as _ACTIVE[-1], silently stealing every subsequent
            # RecordEvent from the outer one). _end_trace failures (a
            # raising on_trace_ready hook) must not skip the restore.
            if self in _ACTIVE:
                idx = len(_ACTIVE) - 1 - _ACTIVE[::-1].index(self)
                del _ACTIVE[idx:]

    def step_info(self, unit: str = "samples"):
        if not self._step_times:
            return "no steps recorded"
        times = [t for t, _ in self._step_times]
        ips = [(n / t) for t, n in self._step_times if n]
        avg = sum(times) / len(times)
        msg = f"avg step {avg * 1000:.2f} ms"
        if ips:
            msg += f", ips {sum(ips) / len(ips):.2f} {unit}/s"
        return msg

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Overview + per-event tables (parity: Profiler.summary →
        profiler_statistic._build_table); device kernel detail lives in the
        exported XPlane trace."""
        text = build_summary(
            self._ledger, self._step_times,
            sorted_by=sorted_by or SortedKeys.CPUTotal,
            time_unit=time_unit)
        print(text)
        return text


@contextlib.contextmanager
def RecordEvent(name: str, event_type=None):
    """parity: paddle.profiler.RecordEvent — annotates the device trace
    (jax TraceAnnotation) AND feeds the host-side statistics ledger AND
    the observability span ring (one annotation feeds all three). The
    interval records even when the body raises — a failing region is
    exactly the one the timeline needs to show."""
    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        t1 = time.perf_counter()
        if _ACTIVE:
            _ACTIVE[-1]._ledger.add(name, t0, t1)
        if _obs_state.enabled():
            _get_tracer().record(name, t0, t1, {"src": "RecordEvent"})


def on_demand_capture(steps: Optional[int] = None,
                      out_dir: Optional[str] = None):
    """Arm a windowed device capture on the observability control plane
    (the same machinery behind ``GET /control/profile?steps=N`` and
    SIGUSR2): the capture starts at the next engine/train step boundary
    and stops ``steps`` boundaries later, so the trace always covers
    whole steps. Returns the controller's status dict. Scheduled
    multi-phase captures stay with :class:`Profiler`; this is the
    "grab me N steps from the live job RIGHT NOW" path."""
    from ..observability import profiling as _obs_profiling

    return _obs_profiling.request_capture(steps=steps, out_dir=out_dir)


def load_profiler_result(path):
    """Load a chrome trace written by export_chrome_tracing back into an
    EventLedger (parity surface: profiler.load_profiler_result; XPlane
    device dumps are for TensorBoard)."""
    import json

    with open(path) as f:
        data = json.load(f)
    ledger = EventLedger()
    for ev in data.get("traceEvents", []):
        t0 = ev["ts"] / 1e6
        ledger.add(ev["name"], t0, t0 + ev.get("dur", 0) / 1e6)
    return ledger


class benchmark:  # noqa: N801  (paddle.profiler.benchmark parity)
    def __init__(self):
        self._t = None

    def begin(self):
        self._t = time.time()

    def end(self):
        return time.time() - self._t


class SummaryView:
    """parity: profiler/profiler.py SummaryView enum — which summary table
    to render."""
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_protobuf(dir_name="./profiler_log", worker_name=None):
    """parity: profiler.export_protobuf — on-trace-ready handler writing
    the raw trace. TPU traces are XPlane protobufs already
    (jax.profiler's output directory); the host event ledger is appended
    as JSON alongside."""
    import os

    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or "worker"
        path = os.path.join(dir_name, f"{name}.pb.json")
        _write_ledger(prof, path)

    handler._dir = dir_name  # Profiler writes the XPlane trace here too
    return handler


def _write_ledger(prof, path):
    import json

    spans = getattr(prof, "_spans", None) or getattr(
        getattr(prof, "_ledger", None), "spans", [])
    with open(path, "w") as f:
        json.dump({"spans": [list(s) for s in spans]}, f)
