"""Profiler statistics summarizer + throughput timer.

Parity: python/paddle/profiler/profiler_statistic.py (the Overview /
Operator summary tables printed by Profiler.summary, SortedKeys sort
options) and python/paddle/profiler/timer.py (Benchmark: reader_cost /
batch_cost / ips rolling averages).

TPU-native framing: device-side kernel timing lives in the XPlane trace
(TensorBoard/Perfetto — jax.profiler); what stays host-side, exactly like
the reference's host tracer statistics, is the RecordEvent span ledger and
the step timer. This module turns those into the reference's tables.
"""
from __future__ import annotations

import time
from collections import defaultdict
from enum import Enum
from typing import Dict, List, Optional, Tuple

__all__ = ["SortedKeys", "EventLedger", "build_summary", "Benchmark"]


class SortedKeys(Enum):
    """parity: profiler_statistic.SortedKeys."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    Calls = 4


class EventLedger:
    """Host-side span ledger filled by RecordEvent while a Profiler is
    recording: (name, t_begin, t_end) triples."""

    def __init__(self):
        self.spans: List[Tuple[str, float, float]] = []

    def add(self, name: str, t0: float, t1: float) -> None:
        self.spans.append((name, t0, t1))

    def clear(self) -> None:
        self.spans.clear()


def _fmt_time(seconds: float, unit: str) -> str:
    scale = {"s": 1.0, "ms": 1e3, "us": 1e6}[unit]
    return f"{seconds * scale:.3f}"


def build_summary(ledger: EventLedger,
                  step_times: Optional[List[Tuple[float, Optional[int]]]]
                  = None,
                  sorted_by: SortedKeys = SortedKeys.CPUTotal,
                  time_unit: str = "ms") -> str:
    """Render the Overview + Event Summary tables (the shape of
    profiler_statistic's _build_table output)."""
    agg: Dict[str, List[float]] = defaultdict(list)
    for name, t0, t1 in ledger.spans:
        agg[name].append(t1 - t0)
    total_window = sum(t for t, _ in step_times) if step_times else \
        sum(sum(v) for v in agg.values())

    rows = []
    for name, durs in agg.items():
        tot = sum(durs)
        rows.append((name, len(durs), tot, tot / len(durs), max(durs),
                     min(durs), 100.0 * tot / total_window
                     if total_window else 0.0))
    keyfn = {
        SortedKeys.CPUTotal: lambda r: -r[2],
        SortedKeys.CPUAvg: lambda r: -r[3],
        SortedKeys.CPUMax: lambda r: -r[4],
        SortedKeys.CPUMin: lambda r: r[5],
        SortedKeys.Calls: lambda r: -r[1],
    }[sorted_by]
    rows.sort(key=keyfn)

    u = time_unit
    header = ["Name", "Calls", f"Total({u})", f"Avg({u})", f"Max({u})",
              f"Min({u})", "Ratio(%)"]
    table = [header] + [
        [name, str(calls), _fmt_time(tot, u), _fmt_time(avg, u),
         _fmt_time(mx, u), _fmt_time(mn, u), f"{ratio:.2f}"]
        for name, calls, tot, avg, mx, mn, ratio in rows]
    widths = [max(len(r[c]) for r in table) for c in range(len(header))]

    def line(row):
        return "  ".join(cell.ljust(w) for cell, w in zip(row, widths))

    out = []
    if step_times:
        times = [t for t, _ in step_times]
        samples = [n for _, n in step_times if n]
        out.append("---------------- Overview Summary ----------------")
        out.append(f"steps: {len(times)}   total: "
                   f"{_fmt_time(sum(times), u)}{u}   avg step: "
                   f"{_fmt_time(sum(times) / len(times), u)}{u}")
        if samples:
            ips = sum(samples) / sum(times)
            out.append(f"throughput: {ips:.2f} samples/s")
        out.append("")
    out.append("----------------- Event Summary ------------------")
    out.append(line(table[0]))
    out.append(line(["-" * w for w in widths]))
    out.extend(line(r) for r in table[1:])
    if not rows:
        out.append("(no RecordEvent spans captured)")
    return "\n".join(out)


class Benchmark:
    """parity: paddle.profiler.timer.Benchmark — rolling reader_cost /
    batch_cost / ips, reported via ``step_info``. Driven by the hapi/fleet
    train loops (timer.step_info per log interval)."""

    def __init__(self, window: int = 100):
        self._window = window
        self.reset()

    def reset(self):
        self._reader_costs: List[float] = []
        self._batch_costs: List[float] = []
        self._samples = 0
        self._t_read0 = None
        self._t_batch0 = None

    # call order per step: before_reader → after_reader → after_step
    def before_reader(self):
        self._t_read0 = time.perf_counter()

    def after_reader(self):
        now = time.perf_counter()
        if self._t_read0 is not None:
            self._reader_costs.append(now - self._t_read0)
            self._reader_costs = self._reader_costs[-self._window:]
        if self._t_batch0 is None:
            self._t_batch0 = self._t_read0
        self._t_read0 = None

    def after_step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._t_batch0 is not None:
            self._batch_costs.append(now - self._t_batch0)
            self._batch_costs = self._batch_costs[-self._window:]
        self._t_batch0 = now
        if num_samples:
            self._samples = num_samples

    def step_info(self, unit: str = "samples") -> str:
        if not self._batch_costs:
            return "no steps recorded"
        avg_batch = sum(self._batch_costs) / len(self._batch_costs)
        msg = []
        if self._reader_costs:
            avg_reader = sum(self._reader_costs) / len(self._reader_costs)
            msg.append(f"reader_cost: {avg_reader:.5f} s")
        msg.append(f"batch_cost: {avg_batch:.5f} s")
        if self._samples:
            msg.append(f"ips: {self._samples / avg_batch:.2f} {unit}/s")
        return ", ".join(msg)
