"""Vision datasets (parity: python/paddle/vision/datasets/ — MNIST, Cifar10,
FashionMNIST, Flowers...). This environment has no network egress, so each
dataset loads from a local file when present and otherwise falls back to a
deterministic synthetic sample generator with the right shapes/classes
(keeps the e2e training paths exercisable anywhere).
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeImageDataset"]


class FakeImageDataset(Dataset):
    """Deterministic synthetic image classification dataset."""

    def __init__(self, num_samples=1024, image_shape=(1, 28, 28), num_classes=10,
                 transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        rng = np.random.RandomState(seed)
        self._labels = rng.randint(0, num_classes, size=num_samples).astype(np.int64)
        self._seeds = rng.randint(0, 2 ** 31 - 1, size=num_samples)

    def __getitem__(self, idx):
        rng = np.random.RandomState(self._seeds[idx])
        label = self._labels[idx]
        # class-dependent mean so the task is learnable
        img = rng.randn(*self.image_shape).astype(np.float32) * 0.5 + \
            (label / self.num_classes - 0.5)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label)

    def __len__(self):
        return self.num_samples


class MNIST(Dataset):
    """parity: python/paddle/vision/datasets/mnist.py. Reads the standard IDX
    files from ``image_path``/``label_path`` if given or found under
    ~/.cache/paddle_tpu/mnist; otherwise synthesizes MNIST-shaped data."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        base = os.path.expanduser("~/.cache/paddle_tpu/mnist")
        tag = "train" if mode == "train" else "t10k"
        image_path = image_path or os.path.join(base, f"{tag}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(base, f"{tag}-labels-idx1-ubyte.gz")
        if os.path.exists(image_path) and os.path.exists(label_path):
            self.images, self.labels = self._load_idx(image_path, label_path)
            self._fake = None
        else:
            n = 4096 if mode == "train" else 512
            self._fake = FakeImageDataset(n, (1, 28, 28), 10,
                                          seed=0 if mode == "train" else 1)
            self.images = None
            self.labels = None

    @staticmethod
    def _load_idx(image_path, label_path):
        op = gzip.open if image_path.endswith(".gz") else open
        with op(image_path, "rb") as f:
            _, num, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), dtype=np.uint8).reshape(num, rows, cols)
        op = gzip.open if label_path.endswith(".gz") else open
        with op(label_path, "rb") as f:
            _, num = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)
        return images, labels

    def __getitem__(self, idx):
        if self._fake is not None:
            return self._fake[idx]
        img = self.images[idx].astype(np.float32)[None] / 255.0
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label)

    def __len__(self):
        return len(self._fake) if self._fake is not None else len(self.images)


class FashionMNIST(MNIST):
    pass


class _CifarBase(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, num_classes=10):
        self.transform = transform
        n = 2048 if mode == "train" else 256
        self._fake = FakeImageDataset(n, (3, 32, 32), num_classes,
                                      seed=2 if mode == "train" else 3)

    def __getitem__(self, idx):
        img, label = self._fake[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self._fake)


class Cifar10(_CifarBase):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        super().__init__(data_file, mode, transform, download, backend, 10)


class Cifar100(_CifarBase):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        super().__init__(data_file, mode, transform, download, backend, 100)
