"""Vision datasets (parity: python/paddle/vision/datasets/ — MNIST, Cifar10,
FashionMNIST, Flowers...). This environment has no network egress, so each
dataset loads from a local file when present and otherwise falls back to a
deterministic synthetic sample generator with the right shapes/classes
(keeps the e2e training paths exercisable anywhere).
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeImageDataset"]


class FakeImageDataset(Dataset):
    """Deterministic synthetic image classification dataset."""

    def __init__(self, num_samples=1024, image_shape=(1, 28, 28), num_classes=10,
                 transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        rng = np.random.RandomState(seed)
        self._labels = rng.randint(0, num_classes, size=num_samples).astype(np.int64)
        self._seeds = rng.randint(0, 2 ** 31 - 1, size=num_samples)

    def __getitem__(self, idx):
        rng = np.random.RandomState(self._seeds[idx])
        label = self._labels[idx]
        # class-dependent mean so the task is learnable
        img = rng.randn(*self.image_shape).astype(np.float32) * 0.5 + \
            (label / self.num_classes - 0.5)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label)

    def __len__(self):
        return self.num_samples


class MNIST(Dataset):
    """parity: python/paddle/vision/datasets/mnist.py. Reads the standard IDX
    files from ``image_path``/``label_path`` if given or found under
    ~/.cache/paddle_tpu/mnist; otherwise synthesizes MNIST-shaped data."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        base = os.path.expanduser("~/.cache/paddle_tpu/mnist")
        tag = "train" if mode == "train" else "t10k"
        image_path = image_path or os.path.join(base, f"{tag}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(base, f"{tag}-labels-idx1-ubyte.gz")
        if os.path.exists(image_path) and os.path.exists(label_path):
            self.images, self.labels = self._load_idx(image_path, label_path)
            self._fake = None
        else:
            n = 4096 if mode == "train" else 512
            self._fake = FakeImageDataset(n, (1, 28, 28), 10,
                                          seed=0 if mode == "train" else 1)
            self.images = None
            self.labels = None

    @staticmethod
    def _load_idx(image_path, label_path):
        op = gzip.open if image_path.endswith(".gz") else open
        with op(image_path, "rb") as f:
            _, num, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), dtype=np.uint8).reshape(num, rows, cols)
        op = gzip.open if label_path.endswith(".gz") else open
        with op(label_path, "rb") as f:
            _, num = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)
        return images, labels

    def __getitem__(self, idx):
        if self._fake is not None:
            return self._fake[idx]
        img = self.images[idx].astype(np.float32)[None] / 255.0
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label)

    def __len__(self):
        return len(self._fake) if self._fake is not None else len(self.images)


class FashionMNIST(MNIST):
    pass


class _CifarBase(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, num_classes=10):
        self.transform = transform
        n = 2048 if mode == "train" else 256
        self._fake = FakeImageDataset(n, (3, 32, 32), num_classes,
                                      seed=2 if mode == "train" else 3)

    def __getitem__(self, idx):
        img, label = self._fake[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self._fake)


class Cifar10(_CifarBase):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        super().__init__(data_file, mode, transform, download, backend, 10)


class Cifar100(_CifarBase):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        super().__init__(data_file, mode, transform, download, backend, 100)


_IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                   ".tiff", ".webp")


def _scan_files(root, extensions, is_valid_file):
    import os

    exts = tuple(e.lower() for e in (extensions or _IMG_EXTENSIONS))
    out = []
    for dirpath, _, files in sorted(os.walk(root)):
        for f in sorted(files):
            path = os.path.join(dirpath, f)
            ok = (is_valid_file(path) if is_valid_file
                  else f.lower().endswith(exts))
            if ok:
                out.append(path)
    return out


class DatasetFolder(Dataset):
    """parity: vision/datasets/folder.py DatasetFolder — samples arranged in
    class subfolders root/<class>/<file>."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os

        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"DatasetFolder: no class folders in {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            for path in _scan_files(os.path.join(root, c), extensions,
                                    is_valid_file):
                self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"DatasetFolder: no valid files under {root}")

    @staticmethod
    def _default_loader(path):
        from ..__init__ import image_load

        img = image_load(path)
        return np.asarray(img)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    """parity: vision/datasets/folder.py ImageFolder — flat folder of
    images, no labels."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os

        self.root = root
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader
        self.samples = _scan_files(root, extensions, is_valid_file)
        if not self.samples:
            raise RuntimeError(f"ImageFolder: no valid files under {root}")

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]


class Flowers(Dataset):
    """parity: vision/datasets/flowers.py — Oxford-102 over local archives
    (no network egress: pass data_file/label_file/setid_file paths)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        import os

        self.transform = transform
        for name, f in (("data_file", data_file), ("label_file", label_file),
                        ("setid_file", setid_file)):
            if not (f and os.path.exists(f)):
                raise RuntimeError(
                    "Flowers: no network egress; pass data_file= (102flowers"
                    " tgz), label_file= (imagelabels.mat), setid_file= "
                    f"(setid.mat) — missing {name}")
        from scipy.io import loadmat

        labels = loadmat(label_file)["labels"][0]
        setid = loadmat(setid_file)
        # NB: the reference deliberately swaps trnid/tstid
        # (vision/datasets/flowers.py MODE_FLAG_MAP: train→tstid)
        key = {"train": "tstid", "valid": "valid", "test": "trnid"}[mode]
        self.indexes = setid[key][0]
        self.labels = labels
        self.data_file = data_file
        import tarfile

        self._tf = tarfile.open(data_file)
        self._names = {os.path.basename(n): n
                       for n in self._tf.getnames() if n.endswith(".jpg")}

    def __getitem__(self, idx):
        import io

        from PIL import Image

        img_id = int(self.indexes[idx])
        name = f"image_{img_id:05d}.jpg"
        data = self._tf.extractfile(self._names[name]).read()
        img = np.asarray(Image.open(io.BytesIO(data)))
        label = int(self.labels[img_id - 1])
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([label], np.int64)

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """parity: vision/datasets/voc2012.py — segmentation pairs from the
    VOCtrainval archive (local file; no egress)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        import os
        import tarfile

        self.transform = transform
        if not (data_file and os.path.exists(data_file)):
            raise RuntimeError(
                "VOC2012: no network egress; pass data_file="
                "(VOCtrainval tar)")
        self._tf = tarfile.open(data_file)
        names = self._tf.getnames()
        base = None
        for n in names:
            if n.endswith("ImageSets/Segmentation/train.txt"):
                base = n[:-len("ImageSets/Segmentation/train.txt")]
                break
        if base is None:
            raise RuntimeError("VOC2012: archive missing Segmentation sets")
        part = {"train": "train.txt", "valid": "val.txt",
                "test": "val.txt"}[mode]
        ids = self._tf.extractfile(
            f"{base}ImageSets/Segmentation/{part}").read().decode().split()
        self._base = base
        self.ids = ids

    def __getitem__(self, idx):
        import io

        from PIL import Image

        iid = self.ids[idx]
        img = np.asarray(Image.open(io.BytesIO(self._tf.extractfile(
            f"{self._base}JPEGImages/{iid}.jpg").read())))
        lbl = np.asarray(Image.open(io.BytesIO(self._tf.extractfile(
            f"{self._base}SegmentationClass/{iid}.png").read())))
        if self.transform is not None:
            img = self.transform(img)
        return img, lbl

    def __len__(self):
        return len(self.ids)


__all__ += ["DatasetFolder", "ImageFolder", "Flowers", "VOC2012"]
