"""MobileNetV3 (parity: python/paddle/vision/models/mobilenetv3.py)."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import flatten


def _make_divisible(v, divisor=8):
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _SE(nn.Layer):
    def __init__(self, ch, squeeze=4):
        super().__init__()
        mid = _make_divisible(ch // squeeze)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, mid, 1)
        self.fc2 = nn.Conv2D(mid, ch, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _InvertedResidual(nn.Layer):
    def __init__(self, inp, exp, oup, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and inp == oup
        Act = nn.Hardswish if act == "hardswish" else nn.ReLU
        layers = []
        if exp != inp:
            layers += [nn.Conv2D(inp, exp, 1, bias_attr=False),
                       nn.BatchNorm2D(exp), Act()]
        layers += [nn.Conv2D(exp, exp, k, stride=stride, padding=k // 2,
                             groups=exp, bias_attr=False),
                   nn.BatchNorm2D(exp)]
        if use_se:
            layers.append(_SE(exp))
        layers += [Act(), nn.Conv2D(exp, oup, 1, bias_attr=False),
                   nn.BatchNorm2D(oup)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_LARGE = [
    # k, exp, out, se, act, stride
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]

_SMALL = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class MobileNetV3(nn.Layer):
    def __init__(self, config, last_channel, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        sc = lambda c: _make_divisible(c * scale)
        self.conv = nn.Sequential(
            nn.Conv2D(3, sc(16), 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(sc(16)), nn.Hardswish())
        blocks = []
        inp = sc(16)
        for k, exp, out, se, act, st in config:
            blocks.append(_InvertedResidual(inp, sc(exp), sc(out), k, st, se,
                                            act))
            inp = sc(out)
        self.blocks = nn.Sequential(*blocks)
        lastconv = sc(config[-1][1])
        self.conv_last = nn.Sequential(
            nn.Conv2D(inp, lastconv, 1, bias_attr=False),
            nn.BatchNorm2D(lastconv), nn.Hardswish())
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(lastconv, last_channel), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_channel, num_classes))

    def forward(self, x):
        x = self.conv_last(self.blocks(self.conv(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 1024, scale, num_classes, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kw):
    return MobileNetV3Large(scale=scale, **kw)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kw):
    return MobileNetV3Small(scale=scale, **kw)
