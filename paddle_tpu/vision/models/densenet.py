"""DenseNet (parity: python/paddle/vision/models/densenet.py)."""
from __future__ import annotations

from ... import nn


class _DenseLayer(nn.Layer):
    def __init__(self, num_input, growth_rate, bn_size, dropout):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(num_input)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(num_input, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        from ...ops.manipulation import concat
        return concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, num_input, num_output):
        super().__init__()
        self.norm = nn.BatchNorm2D(num_input)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(num_input, num_output, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


_CFG = {
    121: (6, 12, 24, 16),
    161: (6, 12, 36, 24),
    169: (6, 12, 32, 32),
    201: (6, 12, 48, 32),
    264: (6, 12, 64, 48),
}


class DenseNet(nn.Layer):
    def __init__(self, layers=121, growth_rate=32, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        block_config = _CFG[layers]
        if layers == 161:
            growth_rate = 48
        num_init = 2 * growth_rate
        self.features = nn.Sequential(
            nn.Conv2D(3, num_init, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(num_init), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        ch = num_init
        self.blocks = nn.LayerList()
        self.transitions = nn.LayerList()
        for i, n in enumerate(block_config):
            block = nn.Sequential(*[
                _DenseLayer(ch + j * growth_rate, growth_rate, bn_size,
                            dropout) for j in range(n)])
            self.blocks.append(block)
            ch += n * growth_rate
            if i != len(block_config) - 1:
                self.transitions.append(_Transition(ch, ch // 2))
                ch //= 2
        self.norm_final = nn.BatchNorm2D(ch)
        self.relu = nn.ReLU()
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        for i, block in enumerate(self.blocks):
            x = block(x)
            if i < len(self.transitions):
                x = self.transitions[i](x)
        x = self.relu(self.norm_final(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops.manipulation import flatten
            x = self.classifier(flatten(x, 1))
        return x


def densenet121(pretrained=False, **kw):
    return DenseNet(121, **kw)


def densenet161(pretrained=False, **kw):
    return DenseNet(161, **kw)


def densenet169(pretrained=False, **kw):
    return DenseNet(169, **kw)


def densenet201(pretrained=False, **kw):
    return DenseNet(201, **kw)


def densenet264(pretrained=False, **kw):
    return DenseNet(264, **kw)
