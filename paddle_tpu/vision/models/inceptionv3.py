"""InceptionV3 (parity: python/paddle/vision/models/inceptionv3.py)."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, flatten


class _Conv(nn.Layer):
    def __init__(self, inp, oup, k, **kw):
        super().__init__()
        self.conv = nn.Conv2D(inp, oup, k, bias_attr=False, **kw)
        self.bn = nn.BatchNorm2D(oup)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _InceptionA(nn.Layer):
    def __init__(self, inp, pool_features):
        super().__init__()
        self.b1 = _Conv(inp, 64, 1)
        self.b5 = nn.Sequential(_Conv(inp, 48, 1), _Conv(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_Conv(inp, 64, 1), _Conv(64, 96, 3, padding=1),
                                _Conv(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _Conv(inp, pool_features, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)], 1)


class _InceptionB(nn.Layer):
    def __init__(self, inp):
        super().__init__()
        self.b3 = _Conv(inp, 384, 3, stride=2)
        self.b3d = nn.Sequential(_Conv(inp, 64, 1), _Conv(64, 96, 3, padding=1),
                                 _Conv(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b3d(x), self.pool(x)], 1)


class _InceptionC(nn.Layer):
    def __init__(self, inp, c7):
        super().__init__()
        self.b1 = _Conv(inp, 192, 1)
        self.b7 = nn.Sequential(
            _Conv(inp, c7, 1), _Conv(c7, c7, (1, 7), padding=(0, 3)),
            _Conv(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            _Conv(inp, c7, 1), _Conv(c7, c7, (7, 1), padding=(3, 0)),
            _Conv(c7, c7, (1, 7), padding=(0, 3)),
            _Conv(c7, c7, (7, 1), padding=(3, 0)),
            _Conv(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _Conv(inp, 192, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)], 1)


class _InceptionD(nn.Layer):
    def __init__(self, inp):
        super().__init__()
        self.b3 = nn.Sequential(_Conv(inp, 192, 1), _Conv(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _Conv(inp, 192, 1), _Conv(192, 192, (1, 7), padding=(0, 3)),
            _Conv(192, 192, (7, 1), padding=(3, 0)),
            _Conv(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b7(x), self.pool(x)], 1)


class _InceptionE(nn.Layer):
    def __init__(self, inp):
        super().__init__()
        self.b1 = _Conv(inp, 320, 1)
        self.b3_stem = _Conv(inp, 384, 1)
        self.b3_a = _Conv(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _Conv(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = nn.Sequential(_Conv(inp, 448, 1),
                                      _Conv(448, 384, 3, padding=1))
        self.b3d_a = _Conv(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = _Conv(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _Conv(inp, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.b3d_stem(x)
        return concat([self.b1(x),
                       concat([self.b3_a(s), self.b3_b(s)], 1),
                       concat([self.b3d_a(d), self.b3d_b(d)], 1),
                       self.bp(x)], 1)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            _Conv(3, 32, 3, stride=2), _Conv(32, 32, 3),
            _Conv(32, 64, 3, padding=1), nn.MaxPool2D(3, stride=2),
            _Conv(64, 80, 1), _Conv(80, 192, 3), nn.MaxPool2D(3, stride=2))
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64), _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048))
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kw):
    return InceptionV3(**kw)
