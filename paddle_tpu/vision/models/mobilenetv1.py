"""MobileNetV1 (parity: python/paddle/vision/models/mobilenetv1.py)."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import flatten


def _dw_sep(inp, oup, stride):
    return nn.Sequential(
        nn.Conv2D(inp, inp, 3, stride=stride, padding=1, groups=inp,
                  bias_attr=False),
        nn.BatchNorm2D(inp), nn.ReLU(),
        nn.Conv2D(inp, oup, 1, bias_attr=False),
        nn.BatchNorm2D(oup), nn.ReLU())


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        s = lambda c: max(8, int(c * scale))
        cfg = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
               (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
               (1024, 1)]
        layers = [nn.Sequential(
            nn.Conv2D(3, s(32), 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(s(32)), nn.ReLU())]
        inp = s(32)
        for c, st in cfg:
            layers.append(_dw_sep(inp, s(c), st))
            inp = s(c)
        self.features = nn.Sequential(*layers)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(inp, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    return MobileNetV1(scale=scale, **kw)
