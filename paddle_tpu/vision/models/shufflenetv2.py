"""ShuffleNetV2 (parity: python/paddle/vision/models/shufflenetv2.py)."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, flatten, reshape, transpose


def channel_shuffle(x, groups):
    B, C, H, W = x.shape
    x = reshape(x, [B, groups, C // groups, H, W])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [B, C, H, W])


class _InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride):
        super().__init__()
        self.stride = stride
        branch = oup // 2
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(inp, inp, 3, stride=stride, padding=1, groups=inp,
                          bias_attr=False),
                nn.BatchNorm2D(inp),
                nn.Conv2D(inp, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), nn.ReLU())
        inp2 = inp if stride > 1 else branch
        self.branch2 = nn.Sequential(
            nn.Conv2D(inp2, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), nn.ReLU(),
            nn.Conv2D(branch, branch, 3, stride=stride, padding=1,
                      groups=branch, bias_attr=False),
            nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), nn.ReLU())

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


_CFG = {
    "x0.25": ([4, 8, 4], [24, 24, 48, 96, 512]),
    "x0.33": ([4, 8, 4], [24, 32, 64, 128, 512]),
    "x0.5": ([4, 8, 4], [24, 48, 96, 192, 1024]),
    "x1.0": ([4, 8, 4], [24, 116, 232, 464, 1024]),
    "x1.5": ([4, 8, 4], [24, 176, 352, 704, 1024]),
    "x2.0": ([4, 8, 4], [24, 244, 488, 976, 2048]),
}


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale="x1.0", act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        repeats, channels = _CFG[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, channels[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(channels[0]), nn.ReLU())
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        inp = channels[0]
        for i, (r, c) in enumerate(zip(repeats, channels[1:4])):
            blocks = [_InvertedResidual(inp, c, 2)]
            blocks += [_InvertedResidual(c, c, 1) for _ in range(r - 1)]
            stages.append(nn.Sequential(*blocks))
            inp = c
        self.stages = nn.LayerList(stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(inp, channels[-1], 1, bias_attr=False),
            nn.BatchNorm2D(channels[-1]), nn.ReLU())
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(channels[-1], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        for s in self.stages:
            x = s(x)
        x = self.conv_last(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return ShuffleNetV2("x0.25", **kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return ShuffleNetV2("x0.33", **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return ShuffleNetV2("x0.5", **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return ShuffleNetV2("x1.0", **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return ShuffleNetV2("x1.5", **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return ShuffleNetV2("x2.0", **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    """parity: vision/models/shufflenetv2.py shufflenet_v2_swish — x1.0
    scale with swish activations."""
    return ShuffleNetV2(scale="x1.0", act="swish", **kw)
