"""GoogLeNet / InceptionV1 (parity: python/paddle/vision/models/googlenet.py)."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, flatten


class _BasicConv(nn.Layer):
    def __init__(self, inp, oup, k, **kw):
        super().__init__()
        self.conv = nn.Conv2D(inp, oup, k, bias_attr=False, **kw)
        self.bn = nn.BatchNorm2D(oup)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _Inception(nn.Layer):
    def __init__(self, inp, c1, c3r, c3, c5r, c5, pp):
        super().__init__()
        self.b1 = _BasicConv(inp, c1, 1)
        self.b2 = nn.Sequential(_BasicConv(inp, c3r, 1),
                                _BasicConv(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_BasicConv(inp, c5r, 1),
                                _BasicConv(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                _BasicConv(inp, pp, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                      axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            _BasicConv(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, stride=2, ceil_mode=True),
            _BasicConv(64, 64, 1),
            _BasicConv(64, 192, 3, padding=1),
            nn.MaxPool2D(3, stride=2, ceil_mode=True))
        self.ince3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.ince3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, ceil_mode=True)
        self.ince4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.ince4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.ince4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.ince4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.ince4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, ceil_mode=True)
        self.ince5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.ince5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.ince3b(self.ince3a(x)))
        x = self.ince4e(self.ince4d(self.ince4c(self.ince4b(self.ince4a(x)))))
        x = self.pool4(x)
        x = self.ince5b(self.ince5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        # reference returns (out, aux1, aux2); aux heads are train-only and
        # omitted here (None placeholders keep the tuple contract)
        return x, None, None


def googlenet(pretrained=False, **kw):
    return GoogLeNet(**kw)
