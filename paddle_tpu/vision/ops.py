"""paddle.vision.ops parity — detection ops.

Reference: python/paddle/vision/ops.py (nms, roi_align, roi_pool, box_coder,
deform_conv2d, distribute_fpn_proposals, PSRoIPool...).
TPU-native: roi_align/roi_pool are gather+interpolate einsums (jit-able,
static shapes); nms is host-side (dynamic output size — not a jit path, same
as the reference's eager usage).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops.creation import _t
from ..ops.dispatch import apply

__all__ = ["nms", "roi_align", "roi_pool", "box_coder", "box_area", "box_iou",
           "distribute_fpn_proposals"]


def box_area(boxes):
    def fn(b):
        return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return apply("box_area", fn, _t(boxes))


def box_iou(boxes1, boxes2):
    def fn(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter)
    return apply("box_iou", fn, _t(boxes1), _t(boxes2))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS (host-side; parity: vision/ops.py nms)."""
    b = np.asarray(_t(boxes)._value, np.float32)
    n = len(b)
    s = (np.asarray(_t(scores)._value, np.float32) if scores is not None
         else np.arange(n, 0, -1, dtype=np.float32))
    cats = (np.asarray(_t(category_idxs)._value) if category_idxs is not None
            else np.zeros(n, np.int64))

    keep_all = []
    for c in np.unique(cats):
        idx = np.where(cats == c)[0]
        order = idx[np.argsort(-s[idx])]
        kept = []
        while len(order):
            i = order[0]
            kept.append(i)
            if len(order) == 1:
                break
            rest = order[1:]
            xx1 = np.maximum(b[i, 0], b[rest, 0])
            yy1 = np.maximum(b[i, 1], b[rest, 1])
            xx2 = np.minimum(b[i, 2], b[rest, 2])
            yy2 = np.minimum(b[i, 3], b[rest, 3])
            inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
            a_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
            a_r = (b[rest, 2] - b[rest, 0]) * (b[rest, 3] - b[rest, 1])
            iou = inter / (a_i + a_r - inter + 1e-10)
            order = rest[iou <= iou_threshold]
        keep_all.extend(kept)
    keep = np.asarray(sorted(keep_all, key=lambda i: -s[i]), np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def _bilinear_sample(feat, y, x):
    """feat [C,H,W]; y/x arbitrary same-shaped grids → [C, *grid]."""
    C, H, W = feat.shape
    y0 = jnp.clip(jnp.floor(y), 0, H - 1)
    x0 = jnp.clip(jnp.floor(x), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    ly, lx = y - y0, x - x0
    y0i, y1i, x0i, x1i = (v.astype(jnp.int32) for v in (y0, y1, x0, x1))

    def g(yi, xi):
        return feat[:, yi, xi]

    v = (g(y0i, x0i) * (1 - ly) * (1 - lx) + g(y0i, x1i) * (1 - ly) * lx
         + g(y1i, x0i) * ly * (1 - lx) + g(y1i, x1i) * ly * lx)
    return v


def roi_align(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """parity: vision/ops.py roi_align. x [N,C,H,W], boxes [R,4] (x1y1x2y2),
    boxes_num [N] → [R, C, out, out]."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    ratio = sampling_ratio if sampling_ratio > 0 else 2

    bn = (np.asarray(_t(boxes_num)._value) if boxes_num is not None
          else np.asarray([_t(boxes).shape[0]]))
    batch_of_box = np.repeat(np.arange(len(bn)), bn)

    def fn(xv, bv):
        off = 0.5 if aligned else 0.0

        def one(args):
            bidx, box = args
            feat = xv[bidx]
            x1, y1, x2, y2 = box * spatial_scale - off
            rw = jnp.maximum(x2 - x1, 1e-3)
            rh = jnp.maximum(y2 - y1, 1e-3)
            bh, bw = rh / oh, rw / ow
            ys = (y1 + bh * (jnp.arange(oh)[:, None, None, None] +
                             (jnp.arange(ratio)[None, :, None, None] + 0.5) / ratio))
            xs = (x1 + bw * (jnp.arange(ow)[None, None, :, None] +
                             (jnp.arange(ratio)[None, None, None, :] + 0.5) / ratio))
            yg = jnp.broadcast_to(ys, (oh, ratio, ow, ratio))
            xg = jnp.broadcast_to(xs, (oh, ratio, ow, ratio))
            v = _bilinear_sample(feat, yg, xg)         # [C, oh, r, ow, r]
            return jnp.mean(v, axis=(2, 4))            # [C, oh, ow]

        bidx_arr = jnp.asarray(batch_of_box)
        return jax.vmap(lambda i, b: one((i, b)))(bidx_arr, bv)

    return apply("roi_align", fn, _t(x), _t(boxes))


def roi_pool(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
             name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bn = (np.asarray(_t(boxes_num)._value) if boxes_num is not None
          else np.asarray([_t(boxes).shape[0]]))
    batch_of_box = np.repeat(np.arange(len(bn)), bn)

    def fn(xv, bv):
        N, C, H, W = xv.shape

        def one(bidx, box):
            feat = xv[bidx]
            x1 = jnp.floor(box[0] * spatial_scale)
            y1 = jnp.floor(box[1] * spatial_scale)
            x2 = jnp.ceil(box[2] * spatial_scale)
            y2 = jnp.ceil(box[3] * spatial_scale)
            rh = jnp.maximum(y2 - y1, 1.0) / oh
            rw = jnp.maximum(x2 - x1, 1.0) / ow
            # dense grid max-pool approximation with 4 samples per bin
            ys = y1 + rh * (jnp.arange(oh)[:, None, None, None]
                            + jnp.asarray([0.25, 0.75])[None, :, None, None])
            xs = x1 + rw * (jnp.arange(ow)[None, None, :, None]
                            + jnp.asarray([0.25, 0.75])[None, None, None, :])
            yg = jnp.broadcast_to(ys, (oh, 2, ow, 2))
            xg = jnp.broadcast_to(xs, (oh, 2, ow, 2))
            v = _bilinear_sample(feat, yg, xg)
            return jnp.max(v, axis=(2, 4))

        return jax.vmap(one)(jnp.asarray(batch_of_box), bv)

    return apply("roi_pool", fn, _t(x), _t(boxes))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    """parity: vision/ops.py box_coder (SSD-style box encode/decode)."""
    def fn(pb, tb, pbv=None):
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                             jnp.log(tw / pw), jnp.log(th / ph)], -1)
            return out / pbv if pbv is not None else out
        # decode
        d = tb * pbv if pbv is not None else tb
        cx = d[..., 0] * pw + pcx
        cy = d[..., 1] * ph + pcy
        w = jnp.exp(d[..., 2]) * pw
        h = jnp.exp(d[..., 3]) * ph
        return jnp.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - norm, cy + h / 2 - norm], -1)

    if prior_box_var is None:
        return apply("box_coder", fn, _t(prior_box), _t(target_box))
    return apply("box_coder", fn, _t(prior_box), _t(target_box),
                 _t(prior_box_var))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (host-side split)."""
    rois = np.asarray(_t(fpn_rois)._value, np.float32)
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    scale = np.sqrt(np.clip(w * h, 1e-6, None))
    lvl = np.floor(refer_level + np.log2(scale / refer_scale + 1e-8))
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, idxs = [], []
    for l in range(min_level, max_level + 1):
        sel = np.where(lvl == l)[0]
        outs.append(Tensor(jnp.asarray(rois[sel])))
        idxs.append(sel)
    order = np.argsort(np.concatenate(idxs)) if idxs else np.zeros(0)
    restore = Tensor(jnp.asarray(order.astype(np.int32)[:, None]))
    nums = [Tensor(jnp.asarray(np.asarray([len(i)], np.int32))) for i in idxs]
    return outs, restore, nums
