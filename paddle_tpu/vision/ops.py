"""paddle.vision.ops parity — detection ops.

Reference: python/paddle/vision/ops.py (nms, roi_align, roi_pool, box_coder,
deform_conv2d, distribute_fpn_proposals, PSRoIPool...).
TPU-native: roi_align/roi_pool are gather+interpolate einsums (jit-able,
static shapes); nms is host-side (dynamic output size — not a jit path, same
as the reference's eager usage).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops.creation import _t
from ..ops.dispatch import apply

__all__ = ["nms", "roi_align", "roi_pool", "box_coder", "box_area", "box_iou",
           "distribute_fpn_proposals", "prior_box", "yolo_box",
           "deform_conv2d", "correlation", "psroi_pool", "matrix_nms",
           "generate_proposals", "yolo_loss",
           "RoIAlign", "RoIPool", "PSRoIPool", "DeformConv2D",
           "read_file", "decode_jpeg",
]


def box_area(boxes):
    def fn(b):
        return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return apply("box_area", fn, _t(boxes))


def box_iou(boxes1, boxes2):
    def fn(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter)
    return apply("box_iou", fn, _t(boxes1), _t(boxes2))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS (host-side; parity: vision/ops.py nms)."""
    b = np.asarray(_t(boxes)._value, np.float32)
    n = len(b)
    s = (np.asarray(_t(scores)._value, np.float32) if scores is not None
         else np.arange(n, 0, -1, dtype=np.float32))
    cats = (np.asarray(_t(category_idxs)._value) if category_idxs is not None
            else np.zeros(n, np.int64))

    keep_all = []
    for c in np.unique(cats):
        idx = np.where(cats == c)[0]
        order = idx[np.argsort(-s[idx])]
        kept = []
        while len(order):
            i = order[0]
            kept.append(i)
            if len(order) == 1:
                break
            rest = order[1:]
            xx1 = np.maximum(b[i, 0], b[rest, 0])
            yy1 = np.maximum(b[i, 1], b[rest, 1])
            xx2 = np.minimum(b[i, 2], b[rest, 2])
            yy2 = np.minimum(b[i, 3], b[rest, 3])
            inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
            a_i = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
            a_r = (b[rest, 2] - b[rest, 0]) * (b[rest, 3] - b[rest, 1])
            iou = inter / (a_i + a_r - inter + 1e-10)
            order = rest[iou <= iou_threshold]
        keep_all.extend(kept)
    keep = np.asarray(sorted(keep_all, key=lambda i: -s[i]), np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def _bilinear_sample(feat, y, x):
    """feat [C,H,W]; y/x arbitrary same-shaped grids → [C, *grid]."""
    C, H, W = feat.shape
    y0 = jnp.clip(jnp.floor(y), 0, H - 1)
    x0 = jnp.clip(jnp.floor(x), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    ly, lx = y - y0, x - x0
    y0i, y1i, x0i, x1i = (v.astype(jnp.int32) for v in (y0, y1, x0, x1))

    def g(yi, xi):
        return feat[:, yi, xi]

    v = (g(y0i, x0i) * (1 - ly) * (1 - lx) + g(y0i, x1i) * (1 - ly) * lx
         + g(y1i, x0i) * ly * (1 - lx) + g(y1i, x1i) * ly * lx)
    return v


def roi_align(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """parity: vision/ops.py roi_align. x [N,C,H,W], boxes [R,4] (x1y1x2y2),
    boxes_num [N] → [R, C, out, out]."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    ratio = sampling_ratio if sampling_ratio > 0 else 2

    bn = (np.asarray(_t(boxes_num)._value) if boxes_num is not None
          else np.asarray([_t(boxes).shape[0]]))
    batch_of_box = np.repeat(np.arange(len(bn)), bn)

    def fn(xv, bv):
        off = 0.5 if aligned else 0.0

        def one(args):
            bidx, box = args
            feat = xv[bidx]
            x1, y1, x2, y2 = box * spatial_scale - off
            rw = jnp.maximum(x2 - x1, 1e-3)
            rh = jnp.maximum(y2 - y1, 1e-3)
            bh, bw = rh / oh, rw / ow
            ys = (y1 + bh * (jnp.arange(oh)[:, None, None, None] +
                             (jnp.arange(ratio)[None, :, None, None] + 0.5) / ratio))
            xs = (x1 + bw * (jnp.arange(ow)[None, None, :, None] +
                             (jnp.arange(ratio)[None, None, None, :] + 0.5) / ratio))
            yg = jnp.broadcast_to(ys, (oh, ratio, ow, ratio))
            xg = jnp.broadcast_to(xs, (oh, ratio, ow, ratio))
            v = _bilinear_sample(feat, yg, xg)         # [C, oh, r, ow, r]
            return jnp.mean(v, axis=(2, 4))            # [C, oh, ow]

        bidx_arr = jnp.asarray(batch_of_box)
        return jax.vmap(lambda i, b: one((i, b)))(bidx_arr, bv)

    return apply("roi_align", fn, _t(x), _t(boxes))


def roi_pool(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
             name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bn = (np.asarray(_t(boxes_num)._value) if boxes_num is not None
          else np.asarray([_t(boxes).shape[0]]))
    batch_of_box = np.repeat(np.arange(len(bn)), bn)

    def fn(xv, bv):
        N, C, H, W = xv.shape

        def one(bidx, box):
            feat = xv[bidx]
            x1 = jnp.floor(box[0] * spatial_scale)
            y1 = jnp.floor(box[1] * spatial_scale)
            x2 = jnp.ceil(box[2] * spatial_scale)
            y2 = jnp.ceil(box[3] * spatial_scale)
            rh = jnp.maximum(y2 - y1, 1.0) / oh
            rw = jnp.maximum(x2 - x1, 1.0) / ow
            # dense grid max-pool approximation with 4 samples per bin
            ys = y1 + rh * (jnp.arange(oh)[:, None, None, None]
                            + jnp.asarray([0.25, 0.75])[None, :, None, None])
            xs = x1 + rw * (jnp.arange(ow)[None, None, :, None]
                            + jnp.asarray([0.25, 0.75])[None, None, None, :])
            yg = jnp.broadcast_to(ys, (oh, 2, ow, 2))
            xg = jnp.broadcast_to(xs, (oh, 2, ow, 2))
            v = _bilinear_sample(feat, yg, xg)
            return jnp.max(v, axis=(2, 4))

        return jax.vmap(one)(jnp.asarray(batch_of_box), bv)

    return apply("roi_pool", fn, _t(x), _t(boxes))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    """parity: vision/ops.py box_coder (SSD-style box encode/decode)."""
    def fn(pb, tb, pbv=None):
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                             jnp.log(tw / pw), jnp.log(th / ph)], -1)
            return out / pbv if pbv is not None else out
        # decode
        d = tb * pbv if pbv is not None else tb
        cx = d[..., 0] * pw + pcx
        cy = d[..., 1] * ph + pcy
        w = jnp.exp(d[..., 2]) * pw
        h = jnp.exp(d[..., 3]) * ph
        return jnp.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - norm, cy + h / 2 - norm], -1)

    if prior_box_var is None:
        return apply("box_coder", fn, _t(prior_box), _t(target_box))
    return apply("box_coder", fn, _t(prior_box), _t(target_box),
                 _t(prior_box_var))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (host-side split)."""
    rois = np.asarray(_t(fpn_rois)._value, np.float32)
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    scale = np.sqrt(np.clip(w * h, 1e-6, None))
    lvl = np.floor(refer_level + np.log2(scale / refer_scale + 1e-8))
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, idxs = [], []
    for l in range(min_level, max_level + 1):
        sel = np.where(lvl == l)[0]
        outs.append(Tensor(jnp.asarray(rois[sel])))
        idxs.append(sel)
    order = np.argsort(np.concatenate(idxs)) if idxs else np.zeros(0)
    restore = Tensor(jnp.asarray(order.astype(np.int32)[:, None]))
    nums = [Tensor(jnp.asarray(np.asarray([len(i)], np.int32))) for i in idxs]
    return outs, restore, nums


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """parity: ops.yaml prior_box (SSD anchor generation). input [N,C,H,W]
    feature map, image [N,C,Him,Wim]; returns (boxes [H,W,A,4],
    variances [H,W,A,4]) normalized to [0,1]."""
    H, W = int(input.shape[2]), int(input.shape[3])
    Him, Wim = int(image.shape[2]), int(image.shape[3])
    step_h = steps[1] or Him / H
    step_w = steps[0] or Wim / W

    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)

    whs = []
    for mi, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            # Caffe/TensorRT order: min, max, then remaining aspect ratios
            whs.append((ms, ms))
            if max_sizes:
                s = np.sqrt(ms * max_sizes[mi])
                whs.append((s, s))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                for mx in max_sizes:
                    s = np.sqrt(ms * mx)
                    whs.append((s, s))
    A = len(whs)
    cx = (np.arange(W) + offset) * step_w
    cy = (np.arange(H) + offset) * step_h
    gx, gy = np.meshgrid(cx, cy)  # [H, W]
    boxes = np.zeros((H, W, A, 4), np.float32)
    for a, (bw, bh) in enumerate(whs):
        boxes[:, :, a, 0] = (gx - bw / 2) / Wim
        boxes[:, :, a, 1] = (gy - bh / 2) / Him
        boxes[:, :, a, 2] = (gx + bw / 2) / Wim
        boxes[:, :, a, 3] = (gy + bh / 2) / Him
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          boxes.shape).copy()
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(var))


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """parity: ops.yaml yolo_box — decode YOLOv3 head predictions into
    boxes [N, H*W*A, 4] and scores [N, H*W*A, class_num]."""
    def fn(v, imgs):
        N, C, H, W = v.shape
        A = len(anchors) // 2
        ioup = None
        if iou_aware:
            # PP-YOLO layout: first A channels are the IoU predictions
            ioup = jax.nn.sigmoid(v[:, :A])
            v = v[:, A:]
        v = v.reshape(N, A, 5 + class_num, H, W)
        gx = (jnp.arange(W) + 0.0)[None, None, None, :]
        gy = (jnp.arange(H) + 0.0)[None, None, :, None]
        sx = scale_x_y
        bx = (jax.nn.sigmoid(v[:, :, 0]) * sx - (sx - 1) / 2 + gx) / W
        by = (jax.nn.sigmoid(v[:, :, 1]) * sx - (sx - 1) / 2 + gy) / H
        anc = jnp.asarray(anchors, jnp.float32).reshape(A, 2)
        bw = jnp.exp(v[:, :, 2]) * anc[None, :, 0, None, None] \
            / (W * downsample_ratio)
        bh = jnp.exp(v[:, :, 3]) * anc[None, :, 1, None, None] \
            / (H * downsample_ratio)
        conf = jax.nn.sigmoid(v[:, :, 4])
        if ioup is not None:
            f = iou_aware_factor
            conf = conf ** (1.0 - f) * ioup ** f
        cls = jax.nn.sigmoid(v[:, :, 5:]) * conf[:, :, None]
        imh = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        imw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x0 = (bx - bw / 2) * imw
        y0 = (by - bh / 2) * imh
        x1 = (bx + bw / 2) * imw
        y1 = (by + bh / 2) * imh
        if clip_bbox:
            x0 = jnp.clip(x0, 0, imw - 1)
            y0 = jnp.clip(y0, 0, imh - 1)
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
        boxes = jnp.stack([x0, y0, x1, y1], axis=-1).reshape(N, -1, 4)
        scores = jnp.moveaxis(cls, 2, -1).reshape(N, -1, class_num)
        keep = (conf.reshape(N, -1) > conf_thresh)[..., None]
        return boxes * keep, scores * keep

    boxes, scores = apply("yolo_box", fn, _t(x), _t(img_size))
    return boxes, scores


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """parity: ops.yaml deformable_conv (v2 when mask given). TPU-native:
    bilinear-sample the input at offset kernel taps (vectorized gather,
    the grid_sample machinery) into an im2col tensor, then one MXU matmul
    with the weights — no per-point scatter kernels."""
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else dilation

    def fn(v, off, w, *rest):
        has_mask = mask is not None
        mk = rest[0] if has_mask else None
        b = rest[-1] if bias is not None else None
        N, C, H, W = v.shape
        Co, Cg, kh, kw = w.shape
        Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        K = kh * kw
        off = off.reshape(N, deformable_groups, K, 2, Ho, Wo)

        base_h = (jnp.arange(Ho) * sh - ph)[None, :, None]
        base_w = (jnp.arange(Wo) * sw - pw)[None, None, :]
        kh_off = (jnp.arange(kh) * dh).repeat(kw).reshape(K, 1, 1)
        kw_off = jnp.tile(jnp.arange(kw) * dw, kh).reshape(K, 1, 1)
        # sample coords [N, dg, K, Ho, Wo]
        py = base_h + kh_off + off[:, :, :, 0]
        px = base_w + kw_off + off[:, :, :, 1]

        def bilinear(coords_y, coords_x):
            y0 = jnp.floor(coords_y)
            x0 = jnp.floor(coords_x)
            wy = coords_y - y0
            wx = coords_x - x0

            def gather(yi, xi):
                inb = ((yi >= 0) & (yi <= H - 1)
                       & (xi >= 0) & (xi <= W - 1))
                yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
                xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
                # v: [N, C, H, W]; index per (n, dg, k, ho, wo)
                vals = v[jnp.arange(N)[:, None, None, None, None],
                         :, yc, xc]          # [N, dg, K, Ho, Wo, C]
                return vals * inb[..., None]

            g00 = gather(y0, x0)
            g01 = gather(y0, x0 + 1)
            g10 = gather(y0 + 1, x0)
            g11 = gather(y0 + 1, x0 + 1)
            top = g00 * (1 - wx)[..., None] + g01 * wx[..., None]
            bot = g10 * (1 - wx)[..., None] + g11 * wx[..., None]
            return top * (1 - wy)[..., None] + bot * wy[..., None]

        samp = bilinear(py, px)              # [N, dg, K, Ho, Wo, C]
        if has_mask:
            samp = samp * mk.reshape(N, deformable_groups, K, Ho,
                                     Wo)[..., None]
        # each deformable group's offsets act on its own channel slice
        dg = deformable_groups
        cpg = C // dg
        samp = jnp.concatenate(
            [samp[:, g, ..., g * cpg:(g + 1) * cpg] for g in range(dg)],
            axis=-1)                          # [N, K, Ho, Wo, C]
        samp = jnp.moveaxis(samp, -1, 1)      # [N, C, K, Ho, Wo]
        wv = w.reshape(groups, Co // groups, Cg, K)
        sv = samp.reshape(N, groups, Cg, K, Ho, Wo)
        out = jnp.einsum("gock,ngckhw->ngohw", wv, sv)
        out = out.reshape(N, Co, Ho, Wo)
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1)
        return out

    args = [_t(x), _t(offset), _t(weight)]
    if mask is not None:
        args.append(_t(mask))
    if bias is not None:
        args.append(_t(bias))
    return apply("deform_conv2d", fn, *args)


def correlation(x1, x2, pad_size=0, kernel_size=1, max_displacement=1,
                stride1=1, stride2=1, corr_type_multiply=1, name=None):
    """parity: ops.yaml correlation (FlowNet cost volume). Geometry follows
    funcs/correlation_funcs.h CorrelationOutputSize + the forward kernel
    (gpu/correlation_kernel.cu): both inputs zero-padded by pad_size; output
    position (oy, ox) reads padded coordinate h1 = oy*stride1 +
    max_displacement; displacement grid radius max_displacement//stride2;
    value is the product mean over the kernel_size patch and channels."""
    if corr_type_multiply != 1:
        raise NotImplementedError(
            "correlation: only multiply mode (the reference kernel's mode)")
    md, s2, k = max_displacement, stride2, int(kernel_size)
    dr = md // s2
    disp = [i * s2 for i in range(-dr, dr + 1)]
    kr = (k - 1) // 2
    border = kr + md

    def fn(a, b):
        N, C, H, W = a.shape
        pH, pW = H + 2 * pad_size, W + 2 * pad_size
        out_h = max(0, -(-(pH - 2 * border) // stride1))
        out_w = max(0, -(-(pW - 2 * border) // stride1))
        ap = jnp.pad(a, ((0, 0), (0, 0), (pad_size, pad_size),
                         (pad_size, pad_size)))
        # extra md margin so every displacement shift stays in-bounds;
        # out-of-range reads are zeros, matching the zero-filled rinput2
        bp = jnp.pad(b, ((0, 0), (0, 0), (pad_size + md, pad_size + md),
                         (pad_size + md, pad_size + md)))
        outs = []
        for dy in disp:
            for dx in disp:
                shifted = jax.lax.dynamic_slice(
                    bp, (0, 0, md + dy, md + dx), ap.shape)
                prod = jnp.mean(ap * shifted, axis=1, keepdims=True)
                if k > 1:  # patch average around each position
                    prod = jax.lax.reduce_window(
                        prod, 0.0, jax.lax.add, (1, 1, k, k),
                        (1, 1, 1, 1),
                        ((0, 0), (0, 0), (kr, k - 1 - kr),
                         (kr, k - 1 - kr))) / (k * k)
                outs.append(prod[:, 0,
                                 md:md + out_h * stride1:stride1,
                                 md:md + out_w * stride1:stride1])
        return jnp.stack(outs, axis=1)   # [N, D*D, Ho, Wo]

    return apply("correlation", fn, _t(x1), _t(x2))


def psroi_pool(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
               name=None):
    """parity: ops.yaml psroi_pool (R-FCN position-sensitive RoI pooling):
    input channels C = out_c * ph * pw; bin (i,j) average-pools its own
    channel group inside the RoI."""
    ph = pw = output_size if isinstance(output_size, int) else None
    if ph is None:
        ph, pw = output_size

    def fn(v, bx):
        N, C, H, W = v.shape
        out_c = C // (ph * pw)
        R = bx.shape[0]
        # map each RoI to its image via boxes_num (reference contract)
        if boxes_num is not None:
            counts = np.asarray(_t(boxes_num)._value)
            img_of = np.repeat(np.arange(len(counts)), counts)
        elif N == 1:
            img_of = np.zeros(R, np.int64)
        else:
            raise ValueError("psroi_pool: boxes_num required when the "
                             "batch has more than one image")
        results = []
        for r in range(R):
            n_img = int(img_of[r])
            x0, y0, x1, y1 = [bx[r, i] * spatial_scale for i in range(4)]
            rh = jnp.maximum(y1 - y0, 1e-3) / ph
            rw = jnp.maximum(x1 - x0, 1e-3) / pw
            bins = []
            yy = jnp.arange(H, dtype=jnp.float32)[:, None]
            xx = jnp.arange(W, dtype=jnp.float32)[None, :]
            for i in range(ph):
                for j in range(pw):
                    in_bin = ((yy >= y0 + i * rh) & (yy < y0 + (i + 1) * rh)
                              & (xx >= x0 + j * rw) & (xx < x0 + (j + 1) * rw))
                    cnt = jnp.maximum(jnp.sum(in_bin), 1.0)
                    grp = v[n_img,
                            (i * pw + j) * out_c:(i * pw + j + 1) * out_c]
                    bins.append(jnp.sum(grp * in_bin[None], axis=(1, 2))
                                / cnt)
            results.append(jnp.stack(bins, 1).reshape(out_c, ph, pw))
        return jnp.stack(results)

    return apply("psroi_pool", fn, _t(x), _t(boxes))


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               name=None):
    """parity: ops.yaml matrix_nms (SOLOv2 soft suppression): decay each
    score by the worst overlap with any higher-scored box of its class —
    fully vectorized, no sequential suppression loop (TPU-friendly)."""
    def fn(bx, sc):
        # bx [M, 4]; sc [cls, M]
        n_cls, M = sc.shape
        area = jnp.maximum(bx[:, 2] - bx[:, 0], 0) \
            * jnp.maximum(bx[:, 3] - bx[:, 1], 0)
        lt = jnp.maximum(bx[:, None, :2], bx[None, :, :2])
        rb = jnp.minimum(bx[:, None, 2:], bx[None, :, 2:])
        wh = jnp.maximum(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter,
                                  1e-9)
        outs = []
        for c in range(n_cls):
            if c == background_label:
                continue
            s = sc[c]
            # pre-filter raw scores, cap at nms_top_k before decay
            s = jnp.where(s > score_threshold, s, 0.0)
            order = jnp.argsort(-s)[:nms_top_k]
            s_sorted = s[order]
            iou_s = iou[order][:, order]
            upper = jnp.triu(iou_s, k=1)           # ious vs higher-scored
            comp = jnp.max(upper, axis=0)          # per-box max overlap
            if use_gaussian:
                decay = jnp.exp(-(comp ** 2) / gaussian_sigma)
            else:
                decay = 1.0 - comp
            dec = s_sorted * decay * (s_sorted > 0)
            keep = dec > post_threshold
            row = jnp.stack([jnp.full_like(dec, c), dec * keep], 1)
            outs.append(jnp.concatenate([row, bx[order]], 1))
        if not outs:  # every class was background — empty detection set
            return jnp.zeros((0, 6), bx.dtype)
        out = jnp.concatenate(outs, 0)  # [*, 6]: label, score, box
        # keep_top_k across classes (zero-score rows sort last)
        final = jnp.argsort(-out[:, 1])[:keep_top_k]
        return out[final]

    return apply("matrix_nms", fn, _t(bboxes), _t(scores))


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """parity: ops.yaml generate_proposals (RPN): decode anchor deltas,
    clip to the image, filter tiny boxes, top-k + NMS. Composition of the
    existing box decode and nms pieces (host-sequenced like the reference's
    CPU kernel; per-image loop)."""
    sc = np.asarray(_t(scores)._value)        # [N, A, H, W]
    bd = np.asarray(_t(bbox_deltas)._value)   # [N, 4A, H, W]
    im = np.asarray(_t(img_size)._value)      # [N, 2] (h, w)
    an = np.asarray(_t(anchors)._value).reshape(-1, 4)
    va = np.asarray(_t(variances)._value).reshape(-1, 4)

    N = sc.shape[0]
    all_rois, rois_num = [], []
    off = 1.0 if pixel_offset else 0.0
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)
        d = bd[n].reshape(-1, 4, sc.shape[2], sc.shape[3]) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)
        aw = an[:, 2] - an[:, 0] + off
        ah = an[:, 3] - an[:, 1] + off
        acx = an[:, 0] + aw / 2
        acy = an[:, 1] + ah / 2
        cx = va[:, 0] * d[:, 0] * aw + acx
        cy = va[:, 1] * d[:, 1] * ah + acy
        w = np.exp(np.minimum(va[:, 2] * d[:, 2], 10.0)) * aw
        h = np.exp(np.minimum(va[:, 3] * d[:, 3], 10.0)) * ah
        boxes = np.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - off, cy + h / 2 - off], 1)
        H_im, W_im = float(im[n, 0]), float(im[n, 1])
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, W_im - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, H_im - off)
        keep = ((boxes[:, 2] - boxes[:, 0] >= min_size)
                & (boxes[:, 3] - boxes[:, 1] >= min_size))
        boxes, s = boxes[keep], s[keep]
        order = np.argsort(-s)[:pre_nms_top_n]
        boxes, s = boxes[order], s[order]
        from ..core.tensor import Tensor as _T
        kept = nms(_T(jnp.asarray(boxes)), nms_thresh,
                   scores=_T(jnp.asarray(s)), top_k=post_nms_top_n)
        kept = np.asarray(kept._value)
        all_rois.append(boxes[kept])
        rois_num.append(len(kept))
    rois = Tensor(jnp.asarray(np.concatenate(all_rois, 0)))
    if return_rois_num:
        return rois, Tensor(jnp.asarray(np.asarray(rois_num, np.int32)))
    return rois


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=False, scale_x_y=1.0, name=None):
    """parity: ops.yaml yolo_loss (YOLOv3 training loss, per feature level).
    x: [N, A*(5+C), H, W] raw head; gt_box: [N, B, 4] normalized
    (cx, cy, w, h); gt_label: [N, B] int; anchors: full anchor list
    (pixels), anchor_mask selects this level's A anchors.

    Per gt: the best wh-IoU anchor (over ALL anchors) is assigned; if it
    belongs to this level, the responsible cell takes xy-BCE, wh-MSE,
    obj-BCE(1) and cls-BCE; other cells take obj-BCE(0) unless their best
    box IoU exceeds ignore_thresh. Returns [N] per-sample loss."""
    mask = list(anchor_mask)
    A = len(mask)
    anc = np.asarray(anchors, np.float32).reshape(-1, 2)

    def fn(v, gb, gl, *rest):
        gs = rest[0] if gt_score is not None else None
        N, _, H, W = v.shape
        C = class_num
        v = v.reshape(N, A, 5 + C, H, W)
        tx, ty = v[:, :, 0], v[:, :, 1]
        tw, th = v[:, :, 2], v[:, :, 3]
        tobj = v[:, :, 4]
        tcls = v[:, :, 5:]

        in_w = W * downsample_ratio
        in_h = H * downsample_ratio
        # decoded boxes for the ignore-mask IoU test (normalized)
        gx = (jax.nn.sigmoid(tx) + jnp.arange(W)[None, None, None, :]) / W
        gy = (jax.nn.sigmoid(ty) + jnp.arange(H)[None, None, :, None]) / H
        lw = anc[mask][:, 0][None, :, None, None]
        lh = anc[mask][:, 1][None, :, None, None]
        gw = jnp.exp(tw) * lw / in_w
        gh = jnp.exp(th) * lh / in_h

        B = gb.shape[1]
        obj_target = jnp.zeros((N, A, H, W))
        ignore = jnp.zeros((N, A, H, W), bool)
        loss_xy = jnp.zeros((N,))
        loss_wh = jnp.zeros((N,))
        loss_cls = jnp.zeros((N,))

        def bce(logit, target):
            return jnp.maximum(logit, 0) - logit * target \
                + jnp.log1p(jnp.exp(-jnp.abs(logit)))

        for b in range(B):
            bx, by, bw, bh = gb[:, b, 0], gb[:, b, 1], gb[:, b, 2], \
                gb[:, b, 3]
            valid = (bw > 0) & (bh > 0)
            score = gs[:, b] if gs is not None else jnp.ones_like(bx)
            # best anchor by wh IoU over ALL anchors (pixel space)
            pw, ph_ = bw * in_w, bh * in_h
            inter = jnp.minimum(pw[:, None], anc[None, :, 0]) \
                * jnp.minimum(ph_[:, None], anc[None, :, 1])
            union = pw[:, None] * ph_[:, None] \
                + anc[None, :, 0] * anc[None, :, 1] - inter
            best = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=1)
            # which of this level's slots (if any)
            level_slot = jnp.full_like(best, -1)
            for s_i, m in enumerate(mask):
                level_slot = jnp.where(best == m, s_i, level_slot)
            on_level = (level_slot >= 0) & valid
            gi = jnp.clip((bx * W).astype(jnp.int32), 0, W - 1)
            gj = jnp.clip((by * H).astype(jnp.int32), 0, H - 1)
            sl = jnp.clip(level_slot, 0, A - 1)
            nidx = jnp.arange(N)
            wgt = (2.0 - bw * bh) * score  # small-box upweight (paddle)

            sel = lambda t: t[nidx, sl, :, gj, gi] if t.ndim == 5 \
                else t[nidx, sl, gj, gi]
            txy_x = bx * W - gi
            txy_y = by * H - gj
            loss_xy = loss_xy + jnp.where(
                on_level, wgt * (bce(sel(tx), txy_x)
                                 + bce(sel(ty), txy_y)), 0.0)
            tw_t = jnp.log(jnp.maximum(
                bw * in_w / jnp.maximum(anc[best][:, 0], 1e-9), 1e-9))
            th_t = jnp.log(jnp.maximum(
                bh * in_h / jnp.maximum(anc[best][:, 1], 1e-9), 1e-9))
            loss_wh = loss_wh + jnp.where(
                on_level, wgt * 0.5 * ((sel(tw) - tw_t) ** 2
                                       + (sel(th) - th_t) ** 2), 0.0)
            smooth = 1.0 / jnp.maximum(C, 1) if use_label_smooth else 0.0
            onehot = jax.nn.one_hot(gl[:, b], C) * (1 - smooth) \
                + smooth / jnp.maximum(C, 1)
            cls_logit = tcls[nidx, sl, :, gj, gi]
            loss_cls = loss_cls + jnp.where(
                on_level, score * jnp.sum(bce(cls_logit, onehot), -1), 0.0)
            obj_target = obj_target.at[nidx, sl, gj, gi].set(
                jnp.where(on_level, score, obj_target[nidx, sl, gj, gi]))
            # ignore mask: predicted boxes overlapping this gt strongly
            ix0 = jnp.maximum(gx - gw / 2, (bx - bw / 2)[:, None, None,
                                                         None])
            iy0 = jnp.maximum(gy - gh / 2, (by - bh / 2)[:, None, None,
                                                         None])
            ix1 = jnp.minimum(gx + gw / 2, (bx + bw / 2)[:, None, None,
                                                         None])
            iy1 = jnp.minimum(gy + gh / 2, (by + bh / 2)[:, None, None,
                                                         None])
            ia = jnp.maximum(ix1 - ix0, 0) * jnp.maximum(iy1 - iy0, 0)
            ua = gw * gh + (bw * bh)[:, None, None, None] - ia
            iou = ia / jnp.maximum(ua, 1e-9)
            ignore = ignore | ((iou > ignore_thresh)
                               & valid[:, None, None, None])

        obj_bce = bce(tobj, obj_target)
        keep = (obj_target > 0) | ~ignore
        loss_obj = jnp.sum(obj_bce * keep, axis=(1, 2, 3))
        return loss_xy + loss_wh + loss_cls + loss_obj

    args = [_t(x), _t(gt_box), _t(gt_label)]
    if gt_score is not None:
        args.append(_t(gt_score))
    return apply("yolo_loss", fn, *args)


# ---------------------------------------------------------------------------
# Layer wrappers + image file ops (parity: vision/ops.py RoIAlign:1316,
# RoIPool, PSRoIPool, DeformConv2D; vision/image.py read_file/decode_jpeg)
# ---------------------------------------------------------------------------
class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        self._a = (output_size, spatial_scale)

    def __call__(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._a[0], self._a[1],
                         aligned=aligned)


class RoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self._a = (output_size, spatial_scale)

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._a[0], self._a[1])


class PSRoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self._a = (output_size, spatial_scale)

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._a[0], self._a[1])


class DeformConv2D:
    """Stateful deformable conv (owns weight/bias like the reference
    Layer)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        import paddle_tpu as paddle

        ks = (kernel_size if isinstance(kernel_size, (list, tuple))
              else (kernel_size, kernel_size))
        self._a = (stride, padding, dilation, deformable_groups, groups)
        self.weight = paddle.create_parameter(
            [out_channels, in_channels // groups, *ks], "float32",
            attr=weight_attr)
        self.bias = (paddle.create_parameter([out_channels], "float32",
                                             attr=bias_attr, is_bias=True)
                     if bias_attr is not False else None)

    def __call__(self, x, offset, mask=None):
        st, pd, dl, dg, g = self._a
        return deform_conv2d(x, offset, self.weight, bias=self.bias,
                             stride=st, padding=pd, dilation=dl,
                             deformable_groups=dg, groups=g, mask=mask)


def read_file(filename, name=None):
    """parity: vision/image.py read_file — raw bytes as a uint8 tensor."""
    import numpy as np

    from ..core.tensor import Tensor
    import jax.numpy as jnp

    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """parity: vision/image.py decode_jpeg — decode a uint8 byte tensor to
    CHW uint8 (PIL backend)."""
    import io

    import numpy as np

    from ..core.tensor import Tensor
    import jax.numpy as jnp

    from PIL import Image

    data = bytes(np.asarray(_t(x)._value).astype(np.uint8))
    img = Image.open(io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))
