"""paddle_tpu.vision (parity: python/paddle/vision/)."""
from __future__ import annotations

from . import datasets, models, transforms  # noqa: F401
from .models import (  # noqa: F401
    AlexNet, LeNet, MobileNetV2, ResNet, VGG, alexnet, mobilenet_v2, resnet18,
    resnet34, resnet50, resnet101, resnet152, vgg11, vgg13, vgg16, vgg19,
)


def set_image_backend(backend):
    pass


def get_image_backend():
    return "numpy"

from . import ops  # noqa: F401,E402


def image_load(path, backend=None):
    """parity: vision/image.py:126 image_load — decode an image file.
    Backends: 'pil' (PIL.Image) or 'cv2'; default reads into a numpy HWC
    array via PIL when available, else a minimal PPM/PGM/BMP reader."""
    backend = backend or get_image_backend() or "pil"
    try:
        from PIL import Image

        img = Image.open(path)
        if backend == "pil":
            return img
        import numpy as np

        arr = np.asarray(img)
        if backend == "cv2" and arr.ndim == 3 and arr.shape[-1] >= 3:
            arr = arr[..., ::-1]  # cv2 convention: BGR (color images only)
        return arr
    except ImportError:
        import numpy as np

        with open(path, "rb") as f:
            magic = f.read(2)
        if magic in (b"P5", b"P6"):  # netpbm
            with open(path, "rb") as f:
                toks = f.read().split(maxsplit=4)
            w, h, maxv = int(toks[1]), int(toks[2]), int(toks[3])
            data = np.frombuffer(toks[4], np.uint8)
            ch = 3 if magic == b"P6" else 1
            return data[:w * h * ch].reshape(h, w, ch).squeeze()
        raise RuntimeError(
            f"image_load: no PIL and unsupported format {magic!r}")
