"""Vision transforms long tail (parity: python/paddle/vision/transforms/
functional.py + transforms.py) — color jitter, grayscale, geometric warps
(affine/rotate/perspective via inverse-mapped coordinates), erase. Host-side
numpy preprocessing like the rest of the package (HWC arrays or PIL)."""
from __future__ import annotations

import numbers
import random as _random

import numpy as np

from . import BaseTransform, _chw

__all__ = [
    "adjust_brightness", "adjust_contrast", "adjust_hue", "to_grayscale",
    "crop", "pad", "erase", "rotate", "affine", "perspective",
    "ColorJitter", "Grayscale", "HueTransform", "SaturationTransform",
    "RandomAffine", "RandomErasing", "RandomPerspective", "RandomRotation",
]


def _as_np(img):
    from ...core.tensor import Tensor

    if isinstance(img, Tensor):
        return np.asarray(img._value), "tensor"
    if isinstance(img, np.ndarray):
        return img, "np"
    return np.asarray(img), "pil"


def _back(arr, kind, ref=None):
    if kind == "pil":
        from PIL import Image

        return Image.fromarray(np.clip(arr, 0, 255).astype(np.uint8))
    if kind == "tensor":
        from ...core.tensor import Tensor

        import jax.numpy as jnp

        return Tensor(jnp.asarray(arr))
    return arr


def _maxval(arr):
    return 255.0 if arr.dtype == np.uint8 or arr.max() > 1.5 else 1.0


# ---------------------------------------------------------------------------
# color ops
# ---------------------------------------------------------------------------
def adjust_brightness(img, brightness_factor):
    """parity: transforms/functional.py adjust_brightness — img * factor."""
    arr, kind = _as_np(img)
    out = np.clip(arr.astype(np.float32) * brightness_factor, 0,
                  _maxval(arr))
    return _back(out.astype(arr.dtype if arr.dtype != np.uint8 else
                            np.float32) if kind != "pil" else out, kind)


def adjust_contrast(img, contrast_factor):
    """Blend with the grayscale mean."""
    arr, kind = _as_np(img)
    f = arr.astype(np.float32)
    gray = f.mean() if f.ndim == 2 else (
        f[..., :3] @ np.array([0.299, 0.587, 0.114], np.float32)).mean()
    out = np.clip((f - gray) * contrast_factor + gray, 0, _maxval(arr))
    return _back(out if kind != "pil" else out, kind)


def _rgb_to_hsv(rgb):
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    maxc = np.max(rgb, -1)
    minc = np.min(rgb, -1)
    v = maxc
    d = maxc - minc
    s = np.where(maxc == 0, 0, d / np.maximum(maxc, 1e-12))
    dz = np.maximum(d, 1e-12)
    rc = (maxc - r) / dz
    gc = (maxc - g) / dz
    bc = (maxc - b) / dz
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(d == 0, 0.0, (h / 6.0) % 1.0)
    return h, s, v


def _hsv_to_rgb(h, s, v):
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = i.astype(np.int32) % 6
    choices = [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
               np.stack([p, v, t], -1), np.stack([p, q, v], -1),
               np.stack([t, p, v], -1), np.stack([v, p, q], -1)]
    out = np.zeros(h.shape + (3,), np.float32)
    for k in range(6):
        out = np.where((i == k)[..., None], choices[k], out)
    return out


def adjust_hue(img, hue_factor):
    """parity: adjust_hue — shift hue channel by hue_factor ∈ [-0.5, 0.5]."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr, kind = _as_np(img)
    mx = _maxval(arr)
    f = arr.astype(np.float32) / mx
    h, s, v = _rgb_to_hsv(f[..., :3])
    h = (h + hue_factor) % 1.0
    out = _hsv_to_rgb(h, s, v) * mx
    if arr.shape[-1] > 3:
        out = np.concatenate([out, arr[..., 3:].astype(np.float32)], -1)
    return _back(out, kind)


def to_grayscale(img, num_output_channels=1):
    """parity: to_grayscale — ITU-R 601 luma."""
    arr, kind = _as_np(img)
    f = arr.astype(np.float32)
    gray = f[..., :3] @ np.array([0.299, 0.587, 0.114], np.float32)
    out = np.repeat(gray[..., None], num_output_channels, axis=-1)
    return _back(out, kind)


# ---------------------------------------------------------------------------
# geometry
# ---------------------------------------------------------------------------
def crop(img, top, left, height, width):
    arr, kind = _as_np(img)
    return _back(arr[top:top + height, left:left + width], kind)


def pad(img, padding, fill=0, padding_mode="constant"):
    """parity: functional.pad — [left, right, top, bottom] (int → all)."""
    arr, kind = _as_np(img)
    if isinstance(padding, numbers.Number):
        pl = pr = pt = pb = int(padding)
    elif len(padding) == 2:
        pl = pr = int(padding[0])
        pt = pb = int(padding[1])
    else:
        pl, pt, pr, pb = (int(p) for p in padding)
    pads = [(pt, pb), (pl, pr)] + [(0, 0)] * (arr.ndim - 2)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return _back(np.pad(arr, pads, mode=mode, **kw), kind)


def erase(img, i, j, h, w, v, inplace=False):
    """parity: functional.erase — fill the region [i:i+h, j:j+w] with v."""
    arr, kind = _as_np(img)
    out = arr if inplace and kind == "np" else arr.copy()
    chw = out.ndim == 3 and out.shape[0] in (1, 3) and \
        out.shape[0] < out.shape[-1]
    val = np.asarray(v._value) if hasattr(v, "_value") else np.asarray(v)
    if chw:
        out[:, i:i + h, j:j + w] = val.reshape(-1, 1, 1) \
            if val.ndim <= 1 else val
    else:
        out[i:i + h, j:j + w] = val.reshape(1, 1, -1) if val.ndim <= 1 \
            else val
    return _back(out, kind)


def _inverse_map(arr, inv_matrix, fill=0.0):
    """Sample arr (H, W, C) at inverse-mapped coordinates (3x3 homography,
    output→input), bilinear."""
    H, W = arr.shape[:2]
    ys, xs = np.mgrid[0:H, 0:W].astype(np.float32)
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], 0).reshape(3, -1)     # x, y, 1
    src = inv_matrix @ coords
    sx = src[0] / np.maximum(np.abs(src[2]), 1e-9) * np.sign(src[2])
    sy = src[1] / np.maximum(np.abs(src[2]), 1e-9) * np.sign(src[2])
    x0 = np.floor(sx).astype(np.int32)
    y0 = np.floor(sy).astype(np.int32)
    wx = sx - x0
    wy = sy - y0
    out = np.zeros((H * W,) + arr.shape[2:], np.float32)
    valid = (sx >= -1) & (sx <= W) & (sy >= -1) & (sy <= H)

    def gather(yy, xx):
        inb = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
        vals = arr[np.clip(yy, 0, H - 1), np.clip(xx, 0, W - 1)].astype(
            np.float32)
        shape = (-1,) + (1,) * (arr.ndim - 2)
        return np.where(inb.reshape(shape), vals, fill)

    shape = (-1,) + (1,) * (arr.ndim - 2)
    out = (gather(y0, x0) * ((1 - wx) * (1 - wy)).reshape(shape)
           + gather(y0, x0 + 1) * (wx * (1 - wy)).reshape(shape)
           + gather(y0 + 1, x0) * ((1 - wx) * wy).reshape(shape)
           + gather(y0 + 1, x0 + 1) * (wx * wy).reshape(shape))
    out = np.where(valid.reshape(shape), out, fill)
    return out.reshape(arr.shape)


def _affine_matrix(angle, translate, scale, shear, center):
    rot = np.deg2rad(angle)
    sx, sy = (np.deg2rad(s) for s in (shear if isinstance(
        shear, (list, tuple)) else (shear, 0.0)))
    cx, cy = center
    tx, ty = translate
    # forward matrix: T(center) R S Sh T(-center) + translate
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    M = np.array([[a * scale, b * scale, 0.0],
                  [c * scale, d * scale, 0.0],
                  [0.0, 0.0, 1.0]], np.float32)
    M[0, 2] = cx + tx - M[0, 0] * cx - M[0, 1] * cy
    M[1, 2] = cy + ty - M[1, 0] * cx - M[1, 1] * cy
    return M


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """parity: functional.affine — rotation/translate/scale/shear warp."""
    arr, kind = _as_np(img)
    H, W = arr.shape[:2]
    if center is None:
        center = ((W - 1) * 0.5, (H - 1) * 0.5)
    M = _affine_matrix(angle, translate, scale, shear, center)
    out = _inverse_map(arr, np.linalg.inv(M), fill=float(
        fill if isinstance(fill, numbers.Number) else fill[0]))
    return _back(out, kind)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """parity: functional.rotate — counter-clockwise degrees."""
    arr, kind = _as_np(img)
    H, W = arr.shape[:2]
    if center is None:
        center = ((W - 1) * 0.5, (H - 1) * 0.5)
    if expand:
        rad = np.deg2rad(angle)
        nW = int(np.ceil(abs(W * np.cos(rad)) + abs(H * np.sin(rad))))
        nH = int(np.ceil(abs(H * np.cos(rad)) + abs(W * np.sin(rad))))
        padl = (nW - W) // 2
        padt = (nH - H) // 2
        arr = np.pad(arr, [(padt, nH - H - padt), (padl, nW - W - padl)]
                     + [(0, 0)] * (arr.ndim - 2), constant_values=fill)
        H, W = nH, nW
        center = ((W - 1) * 0.5, (H - 1) * 0.5)
    M = _affine_matrix(-angle, (0, 0), 1.0, (0.0, 0.0), center)
    out = _inverse_map(arr, np.linalg.inv(M), fill=float(
        fill if isinstance(fill, numbers.Number) else fill[0]))
    return _back(out, kind)


def _homography(src_pts, dst_pts):
    """DLT: 3x3 H with H @ src ~ dst (points as [[x, y], ...])."""
    A = []
    for (x, y), (u, v) in zip(src_pts, dst_pts):
        A.append([x, y, 1, 0, 0, 0, -u * x, -u * y, -u])
        A.append([0, 0, 0, x, y, 1, -v * x, -v * y, -v])
    _, _, Vt = np.linalg.svd(np.asarray(A, np.float64))
    return Vt[-1].reshape(3, 3).astype(np.float32)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """parity: functional.perspective — warp mapping startpoints →
    endpoints."""
    arr, kind = _as_np(img)
    Hm = _homography(startpoints, endpoints)   # start → end
    out = _inverse_map(arr, np.linalg.inv(Hm), fill=float(
        fill if isinstance(fill, numbers.Number) else fill[0]))
    return _back(out, kind)


# ---------------------------------------------------------------------------
# transform classes
# ---------------------------------------------------------------------------
class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        return adjust_hue(img, _random.uniform(-self.value, self.value))


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        arr, kind = _as_np(img)
        f = arr.astype(np.float32)
        gray = (f[..., :3] @ np.array([0.299, 0.587, 0.114],
                                      np.float32))[..., None]
        factor = 1 + _random.uniform(-self.value, self.value)
        out = np.clip(gray + (f[..., :3] - gray) * factor, 0, _maxval(arr))
        if arr.shape[-1] > 3:
            out = np.concatenate([out, f[..., 3:]], -1)
        return _back(out, kind)


class ColorJitter(BaseTransform):
    """parity: transforms.ColorJitter — random brightness/contrast/
    saturation/hue in random order."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.hue = hue

    def _apply_image(self, img):
        ops = []
        if self.brightness:
            f = 1 + _random.uniform(-self.brightness, self.brightness)
            ops.append(lambda im: adjust_brightness(im, f))
        if self.contrast:
            fc = 1 + _random.uniform(-self.contrast, self.contrast)
            ops.append(lambda im: adjust_contrast(im, fc))
        if self.saturation:
            st = SaturationTransform(self.saturation)
            ops.append(st._apply_image)
        if self.hue:
            fh = _random.uniform(-self.hue, self.hue)
            ops.append(lambda im: adjust_hue(im, fh))
        _random.shuffle(ops)
        for op in ops:
            img = op(img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = _random.uniform(*self.degrees)
        return rotate(img, angle, expand=self.expand, center=self.center,
                      fill=self.fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        arr, _ = _as_np(img)
        H, W = arr.shape[:2]
        angle = _random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = _random.uniform(-self.translate[0], self.translate[0]) * W
            ty = _random.uniform(-self.translate[1], self.translate[1]) * H
        sc = 1.0 if self.scale is None else _random.uniform(*self.scale)
        sh = 0.0
        if self.shear is not None:
            s = self.shear
            if isinstance(s, numbers.Number):
                s = (-s, s)
            sh = _random.uniform(s[0], s[1])
        return affine(img, angle, (tx, ty), sc, sh, fill=self.fill,
                      center=self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill

    def _apply_image(self, img):
        if _random.random() >= self.prob:
            return img
        arr, _ = _as_np(img)
        H, W = arr.shape[:2]
        d = self.distortion_scale
        hw = int(W * d / 2)
        hh = int(H * d / 2)

        def jig(x, y):
            return (x + _random.randint(-hw, hw) if hw else x,
                    y + _random.randint(-hh, hh) if hh else y)

        start = [(0, 0), (W - 1, 0), (W - 1, H - 1), (0, H - 1)]
        end = [jig(*p) for p in start]
        return perspective(img, start, end, fill=self.fill)


class RandomErasing(BaseTransform):
    """parity: transforms.RandomErasing — erase a random region with value
    or random noise."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        if _random.random() >= self.prob:
            return img
        arr, kind = _as_np(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and \
            arr.shape[0] < arr.shape[-1]
        H, W = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        C = arr.shape[0] if chw else (arr.shape[2] if arr.ndim == 3 else 1)
        area = H * W
        for _ in range(10):
            target = _random.uniform(*self.scale) * area
            ar = np.exp(_random.uniform(*np.log(self.ratio)))
            h = int(round(np.sqrt(target * ar)))
            w = int(round(np.sqrt(target / ar)))
            if h < H and w < W:
                i = _random.randint(0, H - h)
                j = _random.randint(0, W - w)
                if self.value == "random":
                    v = np.random.normal(size=(C, h, w) if chw
                                         else (h, w, C)).astype(np.float32)
                else:
                    v = np.asarray(self.value, np.float32)
                return erase(img, i, j, h, w, v, inplace=self.inplace)
        return img
