"""Vision transforms (parity: python/paddle/vision/transforms/) — numpy-based
host-side preprocessing."""
from __future__ import annotations

import numbers
import random as _random

import numpy as np

from ...core.tensor import Tensor

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "RandomCrop", "CenterCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
    "RandomResizedCrop", "BrightnessTransform", "ContrastTransform",
    "to_tensor", "normalize", "resize", "hflip", "vflip", "center_crop",
]


def _chw(img):
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _chw(img).astype(np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        is_tensor = isinstance(img, Tensor)
        arr = np.asarray(img._value) if is_tensor else np.asarray(img)
        arr = arr.astype(np.float32)
        if self.data_format == "CHW":
            n = arr.shape[0]
            arr = (arr - self.mean[:n, None, None]) / self.std[:n, None, None]
        else:
            n = arr.shape[-1]
            arr = (arr - self.mean[:n]) / self.std[:n]
        return Tensor(arr) if is_tensor else arr


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def _resize_np(arr, size):
    import jax

    if isinstance(size, int):
        h, w = arr.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    out_shape = (size[0], size[1]) + arr.shape[2:]
    return np.asarray(jax.image.resize(arr.astype(np.float32), out_shape,
                                       method="linear"))


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size

    def _apply_image(self, img):
        return _resize_np(_chw(img), self.size)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = _chw(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = _chw(img)
        if self.padding:
            p = self.padding
            if isinstance(p, int):
                p = (p, p, p, p)
            arr = np.pad(arr, ((p[1], p[3]), (p[0], p[2]), (0, 0)))
        h, w = arr.shape[:2]
        th, tw = self.size
        i = _random.randint(0, max(h - th, 0))
        j = _random.randint(0, max(w - tw, 0))
        return arr[i:i + th, j:j + tw]


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        arr = _chw(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * _random.uniform(*self.scale)
            ar = np.exp(_random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target_area * ar)))
            th = int(round(np.sqrt(target_area / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = _random.randint(0, h - th)
                j = _random.randint(0, w - tw)
                return _resize_np(arr[i:i + th, j:j + tw], self.size)
        return _resize_np(arr, self.size)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if _random.random() < self.prob:
            return np.ascontiguousarray(_chw(img)[:, ::-1])
        return _chw(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if _random.random() < self.prob:
            return np.ascontiguousarray(_chw(img)[::-1])
        return _chw(img)


def hflip(img):
    return np.ascontiguousarray(_chw(img)[:, ::-1])


def vflip(img):
    return np.ascontiguousarray(_chw(img)[::-1])


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return _chw(img).transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill

    def _apply_image(self, img):
        p = self.padding
        if isinstance(p, int):
            p = (p, p, p, p)
        return np.pad(_chw(img), ((p[1], p[3]), (p[0], p[2]), (0, 0)),
                      constant_values=self.fill)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        arr = _chw(img).astype(np.float32)
        f = 1 + _random.uniform(-self.value, self.value)
        return np.clip(arr * f, 0, 255 if arr.max() > 1.5 else 1.0)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        arr = _chw(img).astype(np.float32)
        f = 1 + _random.uniform(-self.value, self.value)
        mean = arr.mean()
        return np.clip((arr - mean) * f + mean, 0, 255 if arr.max() > 1.5 else 1.0)


from .extras import *  # noqa: E402,F401,F403
from .extras import __all__ as _extras_all  # noqa: E402
from . import extras as functional_extras  # noqa: E402,F401
__all__ += _extras_all
