"""paddle.onnx.export (parity: python/paddle/onnx/export.py)."""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export ``layer`` to ONNX via paddle2onnx when available; otherwise
    raise, pointing at the StableHLO export path (jit.save), which is the
    TPU-native serving format."""
    try:
        import paddle2onnx  # noqa: F401
    except ImportError as e:
        raise ModuleNotFoundError(
            "paddle.onnx.export requires `paddle2onnx`, which is not "
            "installed in this environment. For a portable compiled "
            "artifact use paddle_tpu.jit.save(layer, path, input_spec=...) "
            "— it exports StableHLO, the XLA-native interchange format."
        ) from e
