"""paddle.onnx (parity: python/paddle/onnx/) — ONNX export hook.

The reference shells out to paddle2onnx; that toolchain is CUDA-ecosystem
specific and not in this image. The TPU-native interchange format is
StableHLO (paddle_tpu.jit.save) — ONNX export raises with that pointer
unless paddle2onnx is importable."""
from . import export as _export_mod  # noqa: F401
from .export import export  # noqa: F401

__all__ = ["export"]
