"""Real-chip lane for the r18 persistent fused decode megakernel.

The CPU tier-1 lane (tests/test_mega_decode.py) only ever exercises the
Pallas INTERPRETER; this lane proves the compiled Mosaic program — the
whole-layer-stack grid, the double-buffered weight-tile streaming, the
in-call ring DMA append, the fused draft multi-step epilogue — against
the XLA/ragged oracle on the chip, then the acceptance perf claim:
decode-step wall-clock beats the ragged path at batch <= 4 (one launch
per step vs one per layer).

    PADDLE_TPU_DEVICE_TESTS=1 python -m pytest tests_tpu/test_mega_decode_tpu.py -q
"""
import os
import time

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("PADDLE_TPU_DEVICE_TESTS") != "1",
    reason="real-device lane: set PADDLE_TPU_DEVICE_TESTS=1")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models import llama
    cfg = llama.LlamaConfig(
        vocab_size=32768, hidden_size=1536, intermediate_size=6144,
        num_layers=12, num_heads=12, num_kv_heads=4, head_dim=128,
        max_seq_len=2048, remat=False, dtype=jnp.bfloat16)
    params = jax.jit(lambda k: jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16),
        llama.init_params(cfg, k)))(jax.random.PRNGKey(0))
    return params, cfg


def _run(params, cfg, kernel, reqs, *, slots, steps=16, kv="int8",
         **kw):
    from paddle_tpu.serving import LLMEngine
    eng = LLMEngine(params, cfg, max_slots=slots, block_size=64,
                    max_model_len=1024, prompt_buckets=[128, 512, 1024],
                    decode_steps=steps, kv_dtype=kv,
                    decode_kernel=kernel, **kw)
    t0 = time.perf_counter()
    rids = [eng.add_request(p, max_new_tokens=32, temperature=0.0)
            for p in reqs]
    out = eng.run()
    dt = time.perf_counter() - t0
    return [out[r] for r in rids], eng, dt


def test_mega_stream_parity_vs_ragged_on_chip(model):
    """Compiled-Mosaic acceptance: greedy streams through the fused
    megakernel are bit-identical to the ragged path's (bf16 + int8-KV,
    mixed lengths) and the compile cache holds exactly one ("mega",
    flags) variant."""
    params, cfg = model
    rng = np.random.default_rng(0)
    lens = [int(x) for x in np.concatenate(
        [rng.integers(64, 160, size=2), rng.integers(600, 900, size=2)])]
    reqs = [rng.integers(1, 32768, size=ln).tolist() for ln in lens]
    toks_m, eng_m, _ = _run(params, cfg, "mega", reqs, slots=4)
    assert len(eng_m._decode_cache) == 1, sorted(eng_m._decode_cache)
    assert all(k[0] == "mega" for k in eng_m._decode_cache)
    toks_r, _, _ = _run(params, cfg, "ragged", reqs, slots=4)
    assert toks_m == toks_r


def test_mega_auto_small_batch_on_chip(model):
    """auto on TPU at batch <= 4 picks the megakernel; at batch 8 it
    stays on the ragged walk (the small-batch launch-bound regime is
    where the fusion pays)."""
    from paddle_tpu.serving import LLMEngine
    params, cfg = model
    small = LLMEngine(params, cfg, max_slots=4, block_size=64,
                      max_model_len=1024, prompt_buckets=[128])
    assert small._decode_path() == "mega"
    big = LLMEngine(params, cfg, max_slots=8, block_size=64,
                    max_model_len=1024, prompt_buckets=[128])
    assert big._decode_path() == "ragged"


@pytest.mark.parametrize("slots", [1, 4])
def test_mega_decode_beats_ragged_wall_clock_on_chip(model, slots):
    """The acceptance perf claim: decode-step wall-clock through ONE
    persistent launch beats the ragged path's launch-per-layer at
    batch <= 4 (bench row llama-2.6b_serving_megadecode carries the
    regression gate; this is the in-tree ordering check)."""
    params, cfg = model
    rng = np.random.default_rng(1)
    reqs = [rng.integers(1, 32768, size=96).tolist()
            for _ in range(slots)]
    # warm both compile caches before timing
    _run(params, cfg, "mega", reqs, slots=slots)
    _run(params, cfg, "ragged", reqs, slots=slots)
    toks_m, _, dt_m = _run(params, cfg, "mega", reqs, slots=slots)
    toks_r, _, dt_r = _run(params, cfg, "ragged", reqs, slots=slots)
    assert toks_m == toks_r
    n_tok = sum(len(t) for t in toks_m)
    print(f"[batch {slots}] mega {n_tok / dt_m:.1f} tok/s vs ragged "
          f"{n_tok / dt_r:.1f} tok/s")
    assert dt_m < dt_r, (dt_m, dt_r)


def test_mega_spec_draft_fused_on_chip(model):
    """The second fusion target on silicon: draft waves run as one
    persistent multi-step launch and the committed streams match the
    ragged wave's."""
    params, cfg = model
    rng = np.random.default_rng(2)
    reqs = [rng.integers(1, 32768, size=80).tolist() for _ in range(2)]
    toks_m, eng_m, _ = _run(params, cfg, "mega", reqs, slots=2, kv=None,
                            draft_params=params, draft_config=cfg,
                            spec_tokens=4)
    assert eng_m.spec_waves >= 1
    assert "mega" in eng_m._spec_draft_cache
    toks_r, _, _ = _run(params, cfg, "ragged", reqs, slots=2, kv=None,
                        draft_params=params, draft_config=cfg,
                        spec_tokens=4)
    assert toks_m == toks_r
