"""Real-TPU lane, part 2 (VERDICT r2 #8: broaden the on-chip lane).

Covers: MoE train step, serving engine vs dense generate, int8 weight-only
decode, host-offloaded optimizer state (moments in pinned_host), the
layer-wise optimizer-in-backward training path, a bf16 op-numeric slice,
and remat's compiled-memory effect — all on the bench chip.

    PADDLE_TPU_DEVICE_TESTS=1 python -m pytest tests_tpu/ -q
"""
import dataclasses
import os

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("PADDLE_TPU_DEVICE_TESTS") != "1",
    reason="real-device lane: set PADDLE_TPU_DEVICE_TESTS=1")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def test_moe_train_step_on_chip():
    from paddle_tpu.models import moe

    cfg = moe.tiny_moe()
    state = moe.init_train_state(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                cfg.vocab_size)
    step = jax.jit(lambda s, t: moe.train_step(s, t, cfg, lr=1e-2))
    losses = []
    for _ in range(5):
        state, loss = step(state, tokens)
        losses.append(float(np.asarray(loss)))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_serving_engine_matches_dense_on_chip():
    from paddle_tpu.models import llama
    from paddle_tpu.serving import LLMEngine

    cfg = dataclasses.replace(
        llama.tiny_llama(vocab=64, hidden=32, layers=2, heads=4, kv_heads=2,
                         seq=64, ffn=64), dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 64, size=n).tolist() for n in (3, 9, 14)]
    eng = LLMEngine(params, cfg, max_slots=2, block_size=8,
                    max_model_len=64, prompt_buckets=[8, 32])
    ids = [eng.add_request(p, max_new_tokens=5) for p in prompts]
    results = eng.run()
    for rid, p in zip(ids, prompts):
        ref = llama.generate(params, jnp.asarray(np.asarray(p)[None],
                                                 jnp.int32),
                             cfg, max_new_tokens=5, temperature=0.0)
        assert results[rid] == np.asarray(ref)[0, len(p):].tolist()


def test_int8_weight_only_generate_on_chip():
    from paddle_tpu.models import llama

    cfg = llama.tiny_llama(vocab=128, hidden=64, layers=2, heads=2,
                           kv_heads=2, seq=64, ffn=128)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    qp = llama.quantize_params(params)
    assert qp["layers"]["wq"]["q"].dtype == jnp.int8
    toks = jnp.asarray([[5, 7, 11, 13]], jnp.int32)
    cache_d = llama.init_kv_cache(cfg, 1, 32)
    cache_q = llama.init_kv_cache(cfg, 1, 32)
    ld, _ = llama.forward_with_cache(params, toks, cache_d, cfg)
    lq, _ = llama.forward_with_cache(qp, toks, cache_q, cfg)
    d = np.asarray(ld, np.float32)
    q = np.asarray(lq, np.float32)
    assert np.abs(d - q).max() / (np.abs(d).max() + 1e-9) < 0.08
    out = llama.generate(qp, toks, cfg, max_new_tokens=6)
    arr = np.asarray(out)
    assert arr.shape == (1, 10)
    assert ((arr >= 0) & (arr < cfg.vocab_size)).all()


def test_offloaded_moments_live_in_pinned_host_on_chip():
    from paddle_tpu.models import llama
    from paddle_tpu.optimizer.offload import (init_offload_train_state,
                                              make_offload_train_step,
                                              supports_compiled_host_memory)

    assert supports_compiled_host_memory()
    cfg = llama.tiny_llama(vocab=256, hidden=128, layers=2, heads=4,
                           kv_heads=2, seq=64, ffn=256)
    state = init_offload_train_state(llama, cfg, jax.random.PRNGKey(0),
                                     optimizer="adamw",
                                     offload_moments=True)
    step = make_offload_train_step(llama, cfg, optimizer="adamw",
                                   offload_grads=True, offload_moments=True)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 65), 0,
                                cfg.vocab_size)
    losses = []
    for _ in range(3):
        state, loss = step(state, tokens)
        losses.append(float(np.asarray(loss)))
    assert all(np.isfinite(losses))
    kinds = {x.sharding.memory_kind
             for x in jax.tree_util.tree_leaves(state.mu)}
    assert kinds == {"pinned_host"}, kinds
    kinds = {x.sharding.memory_kind
             for x in jax.tree_util.tree_leaves(state.params)}
    assert kinds == {"device"}, kinds


def test_layerwise_step_trains_and_bounds_grad_residency_on_chip():
    """The scale-ladder mechanism (4B-on-16GB): the layer-wise
    optimizer-in-backward step trains correctly on chip, and no compiled
    program in it ever outputs the full gradient tree — the largest
    program output is O(params + one layer), vs the fused step whose
    grad outputs alone equal the whole param tree."""
    from paddle_tpu.models import llama
    from paddle_tpu.optimizer.offload import (init_layerwise_train_state,
                                              make_layerwise_train_step)

    cfg = llama.tiny_llama(vocab=512, hidden=256, layers=4, heads=4,
                           kv_heads=2, seq=256, ffn=512)
    state = init_layerwise_train_state(cfg, jax.random.PRNGKey(0),
                                       param_dtype=jnp.float32)
    # adafactor's relative step: lr=1e-2 oscillates at this scale, 3e-3
    # converges hard (CPU-verified trajectory)
    step = make_layerwise_train_step(cfg, lr=3e-3)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 257), 0,
                                cfg.vocab_size)
    losses = []
    for _ in range(8):
        state, loss = step(state, tokens)
        losses.append(float(np.asarray(loss)))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses

    # compiled-memory assertion: the fused step's temp footprint carries
    # the full grad tree; the layerwise backward's largest single program
    # (one layer) must live well under it
    fused_state = llama.init_train_state(cfg, jax.random.PRNGKey(0),
                                         optimizer="adafactor")
    fused = jax.jit(lambda s, t: llama.train_step(
        s, t, cfg, optimizer="adafactor"))
    ma = fused.lower(fused_state, tokens).compile().memory_analysis()
    if ma is None or ma.temp_size_in_bytes == 0:
        # remote-compile backends (axon tunnel) return zeroed stats
        return
    param_bytes = sum(int(np.prod(p.shape)) * p.dtype.itemsize
                      for p in jax.tree_util.tree_leaves(state.params))
    layer_bytes = param_bytes / cfg.num_layers
    # fused temp includes grads (≈ params) + activations
    assert ma.temp_size_in_bytes > param_bytes * 0.5
    # one layerwise backward program touches ~1/L of the weights
    assert layer_bytes * 3 < param_bytes


def test_remat_cuts_compiled_memory_on_chip():
    from paddle_tpu.models import llama

    base = llama.tiny_llama(vocab=512, hidden=256, layers=4, heads=4,
                            kv_heads=2, seq=512, ffn=1024)

    def temp_bytes(remat):
        cfg = dataclasses.replace(base, remat=remat)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.zeros((8, 513), jnp.int32)
        f = jax.jit(lambda p, t: jax.value_and_grad(llama.loss_fn)(
            p, t, cfg))
        ma = f.lower(params, tokens).compile().memory_analysis()
        if ma is None or ma.temp_size_in_bytes == 0:
            return None   # remote-compile backends return zeroed stats
        return ma.temp_size_in_bytes

    with_remat = temp_bytes(True)
    without = temp_bytes(False)
    if with_remat is None or without is None:
        pytest.skip("backend provides no memory analysis")
    assert with_remat < without, (with_remat, without)


def test_op_numeric_bf16_slice_on_chip():
    """bf16 tolerance slice of the op numeric matrix, on real hardware
    (VPU/MXU paths rather than the CPU emulation the main suite uses)."""
    rng = np.random.default_rng(0)
    x32 = rng.normal(size=(64, 64)).astype(np.float32)
    pos32 = np.abs(x32) + 0.5
    x = jnp.asarray(x32, jnp.bfloat16)
    pos = jnp.asarray(pos32, jnp.bfloat16)

    cases = [
        ("exp", lambda: jnp.exp(x * 0.1), np.exp(x32 * 0.1)),
        ("log", lambda: jnp.log(pos), np.log(pos32)),
        ("rsqrt", lambda: jax.lax.rsqrt(pos), 1 / np.sqrt(pos32)),
        ("tanh", lambda: jnp.tanh(x), np.tanh(x32)),
        ("sigmoid", lambda: jax.nn.sigmoid(x),
         1 / (1 + np.exp(-x32))),
        ("erf", lambda: jax.scipy.special.erf(x),
         np.vectorize(__import__("math").erf)(x32)),
        ("softmax", lambda: jax.nn.softmax(x, -1),
         np.exp(x32 - x32.max(-1, keepdims=True))
         / np.exp(x32 - x32.max(-1, keepdims=True)).sum(-1, keepdims=True)),
        ("matmul", lambda: x @ x, x32 @ x32),
        ("sum", lambda: jnp.sum(x, -1), x32.sum(-1)),
        ("mean", lambda: jnp.mean(x, 0), x32.mean(0)),
        ("max", lambda: jnp.max(x, -1), x32.max(-1)),
        ("cumsum", lambda: jnp.cumsum(x, -1), np.cumsum(x32, -1)),
        ("abs", lambda: jnp.abs(x), np.abs(x32)),
        ("silu", lambda: jax.nn.silu(x), x32 / (1 + np.exp(-x32))),
        ("logsumexp", lambda: jax.scipy.special.logsumexp(x, -1),
         np.log(np.exp(x32 - x32.max(-1, keepdims=True)).sum(-1))
         + x32.max(-1)),
    ]
    for name, fn, expect in cases:
        got = np.asarray(jax.jit(fn)(), np.float32)
        scale = np.abs(np.asarray(expect)).max() + 1e-6
        err = np.abs(got - np.asarray(expect)).max() / scale
        tol = 0.05 if name == "matmul" else 0.02
        assert err < tol, (name, err)


def test_grouped_matmul_matches_ragged_dot_on_chip():
    """The Mosaic grouped matmul (MegaBlocks-style gmm, the dropless-MoE
    GEMM backend on TPU) must match jax.lax.ragged_dot exactly — values
    and both gradients — including uneven and empty groups."""
    from paddle_tpu.kernels.moe_dispatch import grouped_matmul

    m, k, n, E = 1024, 256, 384, 8
    ks = jax.random.split(jax.random.PRNGKey(0), 2)
    x = jax.random.normal(ks[0], (m, k), jnp.bfloat16)
    w = jax.random.normal(ks[1], (E, k, n), jnp.bfloat16)
    gs = jnp.asarray([100, 0, 300, 1, 223, 128, 16, 256], jnp.int32)
    valid = int(gs.sum())

    a = jax.jit(lambda x, w: grouped_matmul(x, w, gs))(x, w)
    b = jax.jit(lambda x, w: jax.lax.ragged_dot(x, w, gs))(x, w)
    np.testing.assert_array_equal(
        np.asarray(a, np.float32)[:valid], np.asarray(b, np.float32)[:valid])

    def loss(f):
        return lambda x, w: jnp.sum(
            f(x, w, gs).astype(jnp.float32)[:valid] ** 2)

    g1 = jax.jit(jax.grad(loss(grouped_matmul), argnums=(0, 1)))(x, w)
    g2 = jax.jit(jax.grad(loss(jax.lax.ragged_dot), argnums=(0, 1)))(x, w)
    for u, v in zip(g1, g2):
        u = np.asarray(u, np.float32)
        v = np.asarray(v, np.float32)
        denom = np.abs(v).max() + 1e-6
        assert np.abs(u - v).max() / denom < 2e-2, np.abs(u - v).max()


def test_grouped_matmul_zeroes_tail_rows_on_chip():
    """sum(gs) < m (the EP-local shape: foreign assignments sort to the
    tail): rows past the last group must be ZEROS like ragged_dot's, not
    uninitialized Pallas output memory — in the value AND in the lhs grad
    (the take-vjp scatter-add would mix garbage into real token grads)."""
    from paddle_tpu.kernels.moe_dispatch import grouped_matmul

    m, k, n, E = 1024, 256, 384, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 2)
    x = jax.random.normal(ks[0], (m, k), jnp.bfloat16)
    w = jax.random.normal(ks[1], (E, k, n), jnp.bfloat16)
    gs = jnp.asarray([100, 0, 300, 1, 128, 16, 64, 32], jnp.int32)
    valid = int(gs.sum())
    assert valid < m

    a = jax.jit(lambda x, w: grouped_matmul(x, w, gs))(x, w)
    b = jax.jit(lambda x, w: jax.lax.ragged_dot(x, w, gs))(x, w)
    np.testing.assert_array_equal(np.asarray(a[valid:], np.float32), 0.0)
    np.testing.assert_array_equal(
        np.asarray(a, np.float32)[:valid], np.asarray(b, np.float32)[:valid])

    # full-array loss (no valid-slice): tail cotangents flow through both
    def loss(f):
        return lambda x, w: jnp.sum(f(x, w, gs).astype(jnp.float32) ** 2)

    g1 = jax.jit(jax.grad(loss(grouped_matmul), argnums=(0, 1)))(x, w)
    g2 = jax.jit(jax.grad(loss(jax.lax.ragged_dot), argnums=(0, 1)))(x, w)
    np.testing.assert_array_equal(np.asarray(g1[0][valid:], np.float32), 0.0)
    for u, v in zip(g1, g2):
        u = np.asarray(u, np.float32)
        v = np.asarray(v, np.float32)
        denom = np.abs(v).max() + 1e-6
        assert np.abs(u - v).max() / denom < 2e-2, np.abs(u - v).max()
