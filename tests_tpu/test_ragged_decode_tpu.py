"""Real-chip lane for the r12 ragged paged-attention decode kernel.

The CPU tier-1 lane (tests/test_paged_attention_ragged.py) only ever
exercises the Pallas INTERPRETER; this lane proves the compiled Mosaic
kernel — the true-length block walk, the pl.when-skipped tail blocks,
the in-register int8 dequant — against the XLA gather oracle on the
chip, then the engine acceptance criteria: greedy stream parity vs the
bucketed path and exactly ONE compiled decode variant per
sampling-flag set.

    PADDLE_TPU_DEVICE_TESTS=1 python -m pytest tests_tpu/test_ragged_decode_tpu.py -q
"""
import os
import time

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("PADDLE_TPU_DEVICE_TESTS") != "1",
    reason="real-device lane: set PADDLE_TPU_DEVICE_TESTS=1")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def _mk(rng, n, bs, hkv, g, d, mb, dtype, lens):
    from paddle_tpu.kernels.paged_attention import PagedKVCache
    nb = n * mb + 1
    kp = jnp.asarray(rng.standard_normal((nb, bs, hkv, d)), dtype)
    vp = jnp.asarray(rng.standard_normal((nb, bs, hkv, d)), dtype)
    table = jnp.asarray(rng.permutation(np.arange(1, nb)).reshape(n, mb),
                        jnp.int32)
    q = jnp.asarray(rng.standard_normal((n, g * hkv, d)), jnp.bfloat16)
    return q, PagedKVCache(kp, vp, table, jnp.asarray(lens, jnp.int32))


def test_ragged_kernel_matches_xla_oracle_on_chip():
    """Compiled-Mosaic numerics (interpret=False on TPU) for the ragged
    block walk vs paged_attention, bf16 pools, serving-sized heads —
    mixed lengths incl. 1 and an exact block boundary."""
    from paddle_tpu.kernels.paged_attention import (paged_attention,
                                                    ragged_paged_decode)
    rng = np.random.default_rng(0)
    N, BS, Hkv, G, D, MB = 8, 64, 8, 3, 128, 8
    lens = [1, BS, BS + 7, 2 * BS, 3 * BS + 11, 5 * BS, MB * BS - 1,
            MB * BS]
    q, cache = _mk(rng, N, BS, Hkv, G, D, MB, jnp.bfloat16, lens)
    want = np.asarray(paged_attention(q, cache), np.float32)
    got = np.asarray(ragged_paged_decode(q, cache), np.float32)
    np.testing.assert_allclose(got, want, atol=5e-2, rtol=5e-2)


def test_ragged_kernel_int8_on_chip():
    """int8 pools: blocks stream unconverted, scales fold in-register —
    vs the dequantize-then-attend oracle."""
    from paddle_tpu.kernels.paged_attention import (PagedKVCache,
                                                    paged_attention,
                                                    ragged_paged_decode)
    from paddle_tpu.kernels.quant_matmul import dequantize_kv, quantize_kv
    rng = np.random.default_rng(1)
    N, BS, Hkv, G, D, MB = 4, 64, 8, 3, 128, 8
    q, cache = _mk(rng, N, BS, Hkv, G, D, MB, jnp.bfloat16,
                   [3, BS + 5, 4 * BS, MB * BS])
    qk, ks = quantize_kv(cache.k_pool)
    qv, vs = quantize_kv(cache.v_pool)
    got = np.asarray(ragged_paged_decode(
        q, PagedKVCache(qk, qv, cache.block_table, cache.lengths),
        ks_pool=ks, vs_pool=vs), np.float32)
    want = np.asarray(paged_attention(q, PagedKVCache(
        dequantize_kv(qk, ks, jnp.bfloat16),
        dequantize_kv(qv, vs, jnp.bfloat16),
        cache.block_table, cache.lengths)), np.float32)
    np.testing.assert_allclose(got, want, atol=6e-2, rtol=6e-2)


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models import llama
    cfg = llama.LlamaConfig(
        vocab_size=32768, hidden_size=1536, intermediate_size=6144,
        num_layers=12, num_heads=12, num_kv_heads=4, head_dim=128,
        max_seq_len=2048, remat=False, dtype=jnp.bfloat16)
    params = jax.jit(lambda k: jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16),
        llama.init_params(cfg, k)))(jax.random.PRNGKey(0))
    return params, cfg


def test_engine_ragged_one_variant_and_stream_parity_on_chip(model):
    """Acceptance: on TPU the default path IS ragged, greedy streams
    match the bucketed path, the compile cache holds exactly one
    variant per flag set across mixed/growing lengths, and the ragged
    engine's decode tok/s on a mixed-length workload is reported (the
    bench row llama-2.6b_serving_mixedlen carries the regression
    gate)."""
    from paddle_tpu.serving import LLMEngine
    params, cfg = model
    rng = np.random.default_rng(0)
    lens = [int(x) for x in np.concatenate(
        [rng.integers(64, 160, size=4), rng.integers(600, 900, size=4)])]
    reqs = [rng.integers(1, 32768, size=ln).tolist() for ln in lens]

    def run(kernel):
        eng = LLMEngine(params, cfg, max_slots=8, block_size=64,
                        max_model_len=1024,
                        prompt_buckets=[128, 512, 1024],
                        decode_steps=16, kv_dtype="int8",
                        decode_kernel=kernel)
        if kernel == "auto":
            assert eng._use_ragged()       # TPU backend picks ragged
        t0 = time.perf_counter()
        rids = [eng.add_request(p, max_new_tokens=32, temperature=0.0)
                for p in reqs]
        out = eng.run()
        dt = time.perf_counter() - t0
        return [out[r] for r in rids], eng, dt

    toks_r, eng_r, dt_r = run("auto")
    assert len(eng_r._decode_cache) == 1, sorted(eng_r._decode_cache)
    assert all(k[0] == "ragged" for k in eng_r._decode_cache)
    toks_b, eng_b, dt_b = run("bucketed")
    assert toks_r == toks_b
    # the ragged walk must read fewer pool bytes than the bucket ceiling
    assert eng_r.kv_read_bytes_total < eng_b.kv_read_bytes_total
    n_tok = sum(len(t) for t in toks_r)
    print(f"ragged {n_tok / dt_r:.1f} tok/s vs bucketed "
          f"{n_tok / dt_b:.1f} tok/s; kv bytes "
          f"{eng_r.kv_read_bytes_total} vs {eng_b.kv_read_bytes_total}")
