"""Real-chip serving-engine throughput vs the raw fused decode loop.

VERDICT r3 contract: at full slots the continuous-batching engine must
deliver >= 0.9x the throughput of `llama.generate_fused` on the same
model/batch/budget (reference serving-decode contract:
paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu).
The CPU lane can't host this comparison — its backend penalizes the paged
gather far more than the TPU does — so it runs here, on the bench chip.

    PADDLE_TPU_DEVICE_TESTS=1 python -m pytest tests_tpu/test_serving_tpu.py -q
"""
import os
import time

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("PADDLE_TPU_DEVICE_TESTS") != "1",
    reason="real-device lane: set PADDLE_TPU_DEVICE_TESTS=1")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

SLOTS, PROMPT, NEW, STEPS = 8, 128, 128, 64


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models import llama
    cfg = llama.LlamaConfig(
        vocab_size=32768, hidden_size=1536, intermediate_size=6144,
        num_layers=12, num_heads=12, num_kv_heads=4, head_dim=128,
        max_seq_len=2048, remat=False, dtype=jnp.bfloat16)
    params = jax.jit(lambda k: jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16),
        llama.init_params(cfg, k)))(jax.random.PRNGKey(0))
    return params, cfg


def test_pallas_paged_kernels_match_xla_oracle_on_chip():
    """Compiled-Mosaic (interpret=False) numerics for the three paged-KV
    kernels vs the XLA reference path — the CPU lane only ever exercises
    the Pallas INTERPRETER, whose semantics can diverge from Mosaic.
    (The engine's hot path uses the hoisted-dense decode since r4; these
    kernels remain the public block-granular API in kernels/.)"""
    from paddle_tpu.kernels.paged_attention import (
        PagedKVCache, paged_append, paged_append_blocks, paged_append_token,
        paged_attention, paged_decode_attention)

    rng = np.random.default_rng(0)
    N, BS, Hkv, G, D, MB = 8, 64, 8, 3, 128, 8
    NB = N * MB + 1
    kp = jnp.asarray(rng.standard_normal((NB, BS, Hkv, D)), jnp.bfloat16)
    vp = jnp.asarray(rng.standard_normal((NB, BS, Hkv, D)), jnp.bfloat16)
    table = jnp.asarray(rng.permutation(np.arange(1, NB)).reshape(N, MB),
                        jnp.int32)
    lens = jnp.asarray(rng.integers(3, MB * BS - 1, size=N), jnp.int32)
    q = jnp.asarray(rng.standard_normal((N, G * Hkv, D)), jnp.bfloat16)
    cache = PagedKVCache(kp, vp, table, lens)

    ref = np.asarray(paged_attention(q, cache), np.float32)
    out = np.asarray(jax.jit(paged_decode_attention)(q, cache), np.float32)
    np.testing.assert_allclose(out, ref, atol=5e-3, rtol=5e-2)

    k_new = jnp.asarray(rng.standard_normal((N, Hkv, D)), jnp.bfloat16)
    v_new = jnp.asarray(rng.standard_normal((N, Hkv, D)), jnp.bfloat16)
    cref = paged_append(cache, k_new, v_new)
    blk = jnp.take_along_axis(table, (lens // BS)[:, None], axis=1)[:, 0]
    kp2, vp2 = jax.jit(paged_append_token)(kp, vp, k_new, v_new, blk,
                                           lens % BS)
    np.testing.assert_array_equal(np.asarray(kp2, np.float32),
                                  np.asarray(cref.k_pool, np.float32))
    np.testing.assert_array_equal(np.asarray(vp2, np.float32),
                                  np.asarray(cref.v_pool, np.float32))

    kb = jnp.asarray(rng.standard_normal((4, BS, Hkv, D)), jnp.bfloat16)
    bids = jnp.asarray(rng.permutation(np.arange(1, NB))[:4], jnp.int32)
    kp3, _ = jax.jit(paged_append_blocks)(kp, vp, kb, kb, bids)
    np.testing.assert_array_equal(np.asarray(kp3, np.float32),
                                  np.asarray(kp.at[bids].set(kb), np.float32))


def test_engine_within_10pct_of_generate_fused(model):
    from paddle_tpu.models import llama
    from paddle_tpu.serving import LLMEngine

    params, cfg = model
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 32768, size=PROMPT).tolist()
               for _ in range(SLOTS)]

    # -- fused fixed-batch loop (one compiled program) ---------------------
    batch = jnp.asarray(np.array(prompts, np.int32))
    out = llama.generate_fused(params, batch, cfg, max_new_tokens=NEW)
    np.asarray(out)                                   # compile + sync
    fused_best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        out = llama.generate_fused(params, batch, cfg, max_new_tokens=NEW)
        np.asarray(out)
        fused_best = min(fused_best, time.perf_counter() - t0)
    fused_tps = SLOTS * NEW / fused_best

    # -- continuous-batching engine at full slots --------------------------
    eng = LLMEngine(params, cfg, max_slots=SLOTS, block_size=64,
                    max_model_len=512, prompt_buckets=[PROMPT],
                    decode_steps=STEPS)
    for p in prompts:                                 # compile + warm
        eng.add_request(p, max_new_tokens=NEW, temperature=0.0)
    eng.run()
    eng_best = float("inf")
    for _ in range(2):
        rids = [eng.add_request(p, max_new_tokens=NEW, temperature=0.0)
                for p in prompts]
        t0 = time.perf_counter()
        res = eng.run()
        dt = time.perf_counter() - t0
        assert all(len(res[r]) == NEW for r in rids)
        eng_best = min(eng_best, dt)
    eng_tps = SLOTS * NEW / eng_best

    print(f"\nengine {eng_tps:.0f} tok/s vs fused {fused_tps:.0f} tok/s "
          f"({eng_tps / fused_tps:.2f}x)")
    assert eng_tps >= 0.9 * fused_tps, (
        f"engine {eng_tps:.0f} tok/s < 0.9x fused {fused_tps:.0f} tok/s")
