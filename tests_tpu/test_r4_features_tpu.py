"""Real-chip lane for the r4 features whose value IS the device behavior:
host-streamed layerwise training (pinned_host param residency) and
segment-compiled eager batching (dispatch-latency amortization).

    PADDLE_TPU_DEVICE_TESTS=1 python -m pytest tests_tpu/test_r4_features_tpu.py -q
"""
import os
import time

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("PADDLE_TPU_DEVICE_TESTS") != "1",
    reason="real-device lane: set PADDLE_TPU_DEVICE_TESTS=1")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def test_streaming_step_params_stay_host_resident():
    """A ~1B model trains via the streaming step with its layer weights in
    pinned_host between steps — the mechanism behind the 8B rung, at a
    size the lane can afford."""
    from paddle_tpu.models import llama
    from paddle_tpu.optimizer.offload import (
        init_streaming_train_state, make_streaming_train_step,
        supports_compiled_host_memory)

    if not supports_compiled_host_memory():
        pytest.skip("no pinned_host memory space on this device")
    cfg = llama.LlamaConfig(
        vocab_size=32768, hidden_size=2048, intermediate_size=5504,
        num_layers=12, num_heads=16, num_kv_heads=8, head_dim=128,
        max_seq_len=1024, remat=True, loss_chunks=4)
    state = init_streaming_train_state(cfg, jax.random.PRNGKey(0))
    for lp in state.layers:
        for leaf in jax.tree_util.tree_leaves(lp):
            assert getattr(leaf.sharding, "memory_kind", None) == \
                "pinned_host", leaf.sharding
    step = make_streaming_train_step(cfg, lr=3e-4)
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 1025), 0,
                              cfg.vocab_size)
    losses = []
    for _ in range(6):
        state, loss = step(state, toks)
        losses.append(float(np.asarray(loss)))
    # adafactor's warmup bounces; the contract here is the MECHANISM
    # (host residency + a training signal), not a convergence curve
    assert all(np.isfinite(losses)), losses
    assert min(losses[1:]) < losses[0], losses
    assert losses[-1] < 2 * losses[0], losses
    # updated weights went BACK to host
    for leaf in jax.tree_util.tree_leaves(state.layers[0]):
        assert getattr(leaf.sharding, "memory_kind", None) == "pinned_host"


def test_segment_scope_amortizes_dispatch_on_chip():
    """Through the remote-attached chip, per-op eager pays a dispatch per
    op; segment_scope batches a multi-op region into ~1. Steady-state the
    win is modest at ~30 ops (~1.5-2x; it grows with region size and is
    ~18x when eager's per-op compile warmup is counted), so the bound
    here is just "not slower" plus exact numerics + cache behavior."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.jit import segment_scope

    blocks = nn.LayerList([nn.Linear(256, 256) for _ in range(16)])

    def fwd(x):
        for b in blocks:
            x = paddle.tanh(b(x))
        return x

    x = paddle.to_tensor(np.random.randn(16, 256).astype("float32"))
    ref = fwd(x)
    ref.numpy()                       # warm eager path, full sync
    t0 = time.perf_counter()
    ref = fwd(x)
    ref_np = ref.numpy()              # the sync IS the cost being timed
    eager_dt = time.perf_counter() - t0

    with segment_scope():             # compile
        out = fwd(x)
        out.numpy()
    t0 = time.perf_counter()
    with segment_scope() as rec:
        out = fwd(x)
        got = out.numpy()
    seg_dt = time.perf_counter() - t0

    np.testing.assert_allclose(got, ref_np, rtol=2e-5, atol=1e-5)
    assert rec.flushes == 1 and rec.compiles == 0
    assert seg_dt < eager_dt * 1.1, (seg_dt, eager_dt)


def test_deepseek_moe_16b_trains_on_one_chip():
    """BASELINE config 5 at its LITERAL scale: DeepSeekMoE-16B (~33 GB of
    bf16 params — 2x HBM) trains via the streaming MoE step with layer
    weights pinned_host-resident. One timed step after compile; the
    capability is the memory scheduling, not a perf rung (PCIe-bound at
    ~1k tok/s on a v5e)."""
    from paddle_tpu.models import moe
    from paddle_tpu.optimizer.offload import (
        init_streaming_moe_train_state, make_streaming_moe_train_step,
        supports_compiled_host_memory)

    if not supports_compiled_host_memory():
        pytest.skip("no pinned_host memory space on this device")
    cfg = moe.deepseek_moe_16b()
    state = init_streaming_moe_train_state(cfg, jax.random.PRNGKey(0))
    for leaf in jax.tree_util.tree_leaves(state.layers[0]):
        assert getattr(leaf.sharding, "memory_kind", None) == "pinned_host"
    step = make_streaming_moe_train_step(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 2049), 0,
                              cfg.vocab_size)
    state, loss = step(state, toks)        # compile + step
    l0 = float(np.asarray(loss))
    state, loss = step(state, toks)
    l1 = float(np.asarray(loss))
    assert np.isfinite(l0) and np.isfinite(l1), (l0, l1)
    for leaf in jax.tree_util.tree_leaves(state.layers[0]):
        assert getattr(leaf.sharding, "memory_kind", None) == "pinned_host"
