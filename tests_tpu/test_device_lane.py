"""Opt-in REAL-TPU test lane (VERDICT r1 weak #4: the main suite runs on the
virtual CPU mesh, so Mosaic/compile regressions were only caught by bench).

Run on the bench host:

    PADDLE_TPU_DEVICE_TESTS=1 python -m pytest tests_tpu/ -q

No conftest here forces a platform — the ambient backend (axon TPU tunnel)
is used as-is. Timing note: through the tunnel only a device-to-host
readback reliably syncs, so every check reads values back via np.asarray.
"""
import os

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("PADDLE_TPU_DEVICE_TESTS") != "1",
    reason="real-device lane: set PADDLE_TPU_DEVICE_TESTS=1")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def _on_tpu():
    return jax.devices()[0].platform == "tpu"


def test_device_is_tpu():
    assert _on_tpu(), jax.devices()


def test_pallas_flash_attention_matches_reference_on_chip():
    """Mosaic-compiled (non-interpret) FA2 fwd+bwd vs einsum math, bf16."""
    from paddle_tpu.kernels.pallas_attention import flash_attention_fwd

    B, S, H, D = 2, 512, 4, 128
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, S, H, D), jnp.bfloat16)

    def ref(q, k, v):
        s = jnp.einsum("bshd,bthd->bhst", q, k) / np.sqrt(D)
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s.astype(jnp.float32), -1).astype(q.dtype)
        return jnp.einsum("bhst,bthd->bshd", p, v)

    out = jax.jit(lambda q, k, v: flash_attention_fwd(q, k, v, causal=True))(
        q, k, v)
    expect = jax.jit(ref)(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(expect, np.float32),
                               atol=2e-2, rtol=2e-2)

    def loss_k(f):
        return lambda q, k, v: jnp.sum(f(q, k, v).astype(jnp.float32) ** 2)

    g1 = jax.jit(jax.grad(loss_k(
        lambda q, k, v: flash_attention_fwd(q, k, v, causal=True)),
        argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.jit(jax.grad(loss_k(ref), argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g1, g2):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        denom = np.abs(b).max() + 1e-6
        assert np.abs(a - b).max() / denom < 5e-2


def test_llama_train_step_on_chip():
    from paddle_tpu.models import llama

    cfg = llama.tiny_llama(vocab=512, hidden=256, layers=2, heads=2,
                           kv_heads=2, seq=256, ffn=512)
    state = llama.init_train_state(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 257), 0,
                                cfg.vocab_size)
    step = jax.jit(lambda s, t: llama.train_step(s, t, cfg, lr=1e-2))
    losses = []
    for _ in range(5):
        state, loss = step(state, tokens)
        losses.append(float(np.asarray(loss)))  # d2h sync each step
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_generate_on_chip():
    from paddle_tpu.models import llama

    cfg = llama.tiny_llama(vocab=128, hidden=64, layers=2, heads=2,
                           kv_heads=2, seq=64, ffn=128)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    prompt = jnp.asarray([[5, 7, 11]], jnp.int32)
    out = llama.generate(params, prompt, cfg, max_new_tokens=8)
    arr = np.asarray(out)
    assert arr.shape == (1, 11)
    assert (arr >= 0).all() and (arr < cfg.vocab_size).all()


def test_long_context_flash_attention_8k_on_chip():
    """Long-context lane: Mosaic FA2 at seq 8192 (256 MB of f32 scores per head
    if materialized — the flash tiling must not) fwd+bwd against the
    blockwise-safe reference computed in slices."""
    from paddle_tpu.kernels.pallas_attention import flash_attention_fwd

    B, S, H, D = 1, 8192, 2, 128
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, S, H, D), jnp.bfloat16)

    out = jax.jit(lambda a, b, c: flash_attention_fwd(a, b, c, causal=True))(
        q, k, v)
    got = np.asarray(out)

    # reference computed in query slices (keeps the dense score slice
    # small); lo rides as a traced operand so one compilation serves all
    # three slices
    @jax.jit
    def ref_slice(qs, kv_k, kv_v, lo):
        scores = jnp.einsum("bshd,bthd->bhst", qs.astype(jnp.float32),
                            kv_k.astype(jnp.float32)) / np.sqrt(D)
        col = jnp.arange(S)[None, None, None, :]
        row = (lo + jnp.arange(qs.shape[1]))[None, None, :, None]
        scores = jnp.where(col <= row, scores, -1e30)
        p = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhst,bthd->bshd", p, kv_v.astype(jnp.float32))

    for lo in (0, 4096, 8192 - 512):
        want = np.asarray(ref_slice(q[:, lo:lo + 512], k, v, lo))
        np.testing.assert_allclose(got[:, lo:lo + 512].astype(np.float32),
                                   want, rtol=8e-2, atol=8e-3)

    # backward (dq AND dk/dv kernels) compiles with finite grads at 8k
    def loss(a, b, c):
        return jnp.sum(flash_attention_fwd(a, b, c, causal=True)
                       .astype(jnp.float32) ** 2)

    gq, gk, gv = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    for g in (gq, gk, gv):
        assert bool(np.isfinite(np.asarray(g, np.float32)).all())


def test_profiler_trace_on_chip(tmp_path):
    """§5.1 hardware evidence: paddle.profiler captures a device trace of a
    real train step and exports chrome-trace + the XPlane dump."""
    import paddle_tpu as paddle
    from paddle_tpu.models import llama

    cfg = llama.tiny_llama(vocab=512, hidden=256, layers=2, heads=4,
                           kv_heads=2, seq=128, ffn=512)
    state = llama.init_train_state(cfg, jax.random.PRNGKey(0))
    tok = jax.random.randint(jax.random.PRNGKey(1), (4, 129), 0,
                             cfg.vocab_size)
    step = jax.jit(lambda s, t: llama.train_step(s, t, cfg))
    state, loss = step(state, tok)  # compile outside the trace
    float(np.asarray(loss))

    out_dir = str(tmp_path / "trace")
    prof = paddle.profiler.Profiler(
        targets=[paddle.profiler.ProfilerTarget.CPU,
                 paddle.profiler.ProfilerTarget.GPU],
        on_trace_ready=paddle.profiler.export_chrome_tracing(out_dir))
    prof.start()
    with paddle.profiler.RecordEvent("train_step"):
        state, loss = step(state, tok)
        float(np.asarray(loss))
    prof.stop()
    written = []
    for root, _, files in os.walk(out_dir):
        written += [os.path.join(root, f) for f in files]
    assert any(f.endswith(".json") for f in written), written
