"""Real-TPU lane: measured gmm-tiling autotune (kernels/gmm_autotune.py).

The CPU tier-1 suite pins the autotuner's *logic* (candidate envelope,
winner selection, persistence round-trip); this lane pins the part that
needs a chip — a measured winner runs the actual Mosaic kernel and is
numerically interchangeable with the heuristic tiling and with
jax.lax.ragged_dot.

    PADDLE_TPU_DEVICE_TESTS=1 python -m pytest tests_tpu/ -q
"""
import os

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("PADDLE_TPU_DEVICE_TESTS") != "1",
    reason="real-device lane: set PADDLE_TPU_DEVICE_TESTS=1")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def test_autotuned_gmm_matches_heuristic_and_ragged_dot_on_chip(tmp_path):
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.kernels import gmm_autotune
    from paddle_tpu.kernels.moe_dispatch import grouped_matmul

    m, k, n, E = 1024, 256, 384, 8
    ks = jax.random.split(jax.random.PRNGKey(0), 2)
    x = jax.random.normal(ks[0], (m, k), jnp.bfloat16)
    w = jax.random.normal(ks[1], (E, k, n), jnp.bfloat16)
    gs = jnp.asarray([100, 0, 300, 1, 223, 128, 16, 256], jnp.int32)

    set_flags({"jit_cache_dir": str(tmp_path)})
    try:
        gmm_autotune.clear()
        set_flags({"moe_gmm_autotune": True})
        y_tuned = np.asarray(jax.jit(
            lambda x, w: grouped_matmul(x, w, gs))(x, w), np.float32)
        # the measurement really happened and persisted
        ents = gmm_autotune.entries()
        assert ents and ents[0][1] == "measured", ents
        assert os.path.exists(os.path.join(str(tmp_path),
                                           "gmm_tilings.json"))

        jax.clear_caches()
        set_flags({"moe_gmm_autotune": False})
        y_heur = np.asarray(jax.jit(
            lambda x, w: grouped_matmul(x, w, gs))(x, w), np.float32)

        y_ref = np.asarray(jax.jit(
            lambda x, w: jax.lax.ragged_dot(x, w, gs))(x, w), np.float32)
        valid = int(gs.sum())
        # different tilings only reorder the bf16 accumulation
        denom = np.abs(y_ref).max() + 1e-6
        assert np.abs(y_tuned - y_heur)[:valid].max() / denom < 2e-2
        assert np.abs(y_tuned - y_ref)[:valid].max() / denom < 2e-2
    finally:
        set_flags({"moe_gmm_autotune": True, "jit_cache_dir": ""})
        gmm_autotune.clear()
