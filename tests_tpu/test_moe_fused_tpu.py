"""Real-TPU lane: the fused scatter-free MoE dispatch (kernels/moe_fused.py).

The CPU tier-1 suite pins the fused pipeline's *math* (gather-based
combine, padded layout, int8 scale folding, interpret-mode kernel); this
lane pins the parts that need a chip:

- the compiled Pallas gather-GMM kernel (DMA row gather folded into the
  grouped-GEMM lhs load) against take + the Mosaic grouped matmul;
- the full fused_moe_ffn Pallas path (counter path="pallas") against the
  XLA rewrite and the gmm dispatch, values and grads;
- int8 expert weights streaming unconverted through the kernel;
- the measured dispatch-form pick running real fwd+bwd timings and
  persisting a winner.

    PADDLE_TPU_DEVICE_TESTS=1 python -m pytest tests_tpu/ -q
"""
import os

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("PADDLE_TPU_DEVICE_TESTS") != "1",
    reason="real-device lane: set PADDLE_TPU_DEVICE_TESTS=1")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def _operands(T=2048, h=512, E=8, f=256, k=2, seed=0):
    from paddle_tpu.kernels import moe_dispatch as md

    x, rw, eg, eu, ed = md.make_moe_operands(T, h, E, f, jnp.bfloat16,
                                             seed=seed)
    r = md.fused_routing(x, rw, k)
    return x, r, eg, eu, ed


def test_gather_gmm_kernel_matches_take_plus_gmm_on_chip():
    from paddle_tpu.kernels import moe_dispatch as md
    from paddle_tpu.kernels import moe_fused as mf

    x, r, eg, eu, ed = _operands()
    T, k = r.idx.shape
    A = T * k
    E = eg.shape[0]
    f = eg.shape[-1]
    esorted = r.flat_e[r.order]
    inv2d = mf._inverse_permutation(r.order).reshape(T, k)
    ws = r.weights.reshape(A)[r.order].astype(jnp.float32)
    tok_pad, _ws, _es, _inv, gs_pad = mf._pad_layout(
        r.gs, r.tok, ws, esorted, inv2d, E)
    Wcat = jnp.concatenate([eg, eu], -1)
    gid = mf._tile_gids(gs_pad, tok_pad.shape[0], mf._KTM)

    out = np.asarray(jax.jit(
        lambda x, w: mf.gather_gmm(x, tok_pad, w, gid))(x, Wcat),
        np.float32)
    ref = np.asarray(jax.jit(
        lambda x, w: jax.lax.ragged_dot(
            jnp.take(x, tok_pad, axis=0), w, gs_pad))(x, Wcat), np.float32)
    valid = (np.arange(tok_pad.shape[0]) < int(jnp.sum(gs_pad)))[:, None]
    err = np.abs(np.where(valid, out - ref, 0.0))
    assert err.max() < 5e-2 * max(np.abs(ref).max(), 1.0)


def test_fused_pallas_path_matches_xla_and_gmm_on_chip():
    import paddle_tpu.observability as obs
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.kernels import moe_dispatch as md
    from paddle_tpu.kernels import moe_fused as mf
    from paddle_tpu.observability.metrics import counter

    x, r, eg, eu, ed = _operands(seed=1)
    obs.enable()
    try:
        c = counter("moe_gmm_fused_dispatch_total").labels(path="pallas")
        c0 = c.value
        y_pallas = jax.jit(lambda *a: mf.fused_moe_ffn(*a, routing=r))(
            x, r.weights, r.idx, eg, eu, ed)
        took_pallas = c.value > c0
        set_flags({"moe_fused_kernel": False})
        try:
            y_xla = jax.jit(lambda *a: mf.fused_moe_ffn(*a, routing=r))(
                x, r.weights, r.idx, eg, eu, ed)
        finally:
            set_flags({"moe_fused_kernel": True})
    finally:
        obs.disable()
    y_gmm = jax.jit(lambda *a: md.dropless_moe_ffn(*a, routing=r))(
        x, r.weights, r.idx, eg, eu, ed)
    a, b, g = (np.asarray(v, np.float32) for v in (y_pallas, y_xla, y_gmm))
    scale = max(np.abs(g).max(), 1.0)
    assert np.abs(a - b).max() < 5e-2 * scale
    assert np.abs(a - g).max() < 5e-2 * scale
    assert took_pallas, "TPU lane must exercise the compiled kernel"

    # grads through the pallas path track the gmm dispatch
    ct = jax.random.normal(jax.random.PRNGKey(5), x.shape)

    def loss(fn):
        return lambda x, eg, eu, ed: jnp.sum(
            fn(x, r.weights, r.idx, eg, eu, ed, routing=r)
            .astype(jnp.float32) * ct)

    gp = jax.jit(jax.grad(loss(mf.fused_moe_ffn),
                          argnums=(0, 1, 2, 3)))(x, eg, eu, ed)
    gg = jax.jit(jax.grad(loss(md.dropless_moe_ffn),
                          argnums=(0, 1, 2, 3)))(x, eg, eu, ed)
    for p, q, name in zip(gp, gg, ("x", "gate", "up", "down")):
        p, q = np.asarray(p, np.float32), np.asarray(q, np.float32)
        assert np.abs(p - q).max() < 5e-2 * max(np.abs(q).max(), 1e-3), name


def test_int8_experts_through_kernel_on_chip():
    from paddle_tpu.kernels import moe_fused as mf
    from paddle_tpu.kernels.quant_matmul import quantize_grouped

    x, r, eg, eu, ed = _operands(seed=2)
    qg, qu, qd = (quantize_grouped(eg, 1), quantize_grouped(eu, 1),
                  quantize_grouped(ed, 2))
    y16 = np.asarray(jax.jit(
        lambda *a: mf.fused_moe_ffn(*a, routing=r))(
            x, r.weights, r.idx, eg, eu, ed), np.float32)
    y8 = np.asarray(jax.jit(
        lambda x, w: mf.fused_moe_ffn(x, w, r.idx, qg, qu, qd, routing=r))(
            x, r.weights), np.float32)
    assert np.abs(y8 - y16).max() < 6e-2 * max(np.abs(y16).max(), 1.0)


def test_dispatch_form_measured_on_chip(tmp_path):
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.jit import cache as jcache
    from paddle_tpu.kernels import moe_dispatch as md

    set_flags({"jit_cache_dir": str(tmp_path)})
    try:
        md.clear_form_cache()
        form = md.pick_dispatch_form(2048, 2, 8, 512, 256, jnp.bfloat16,
                                     dense_ok=True)
        assert form in ("fused", "gmm", "dense")
        doc = jcache.load_json(md._FORM_PERSIST, schema=md._FORM_SCHEMA)
        assert doc and all("winner" in e for e in doc.values())
        (ent,) = doc.values()
        assert set(ent["ms"]) >= {"fused", "gmm"}
    finally:
        md.clear_form_cache()
        set_flags({"jit_cache_dir": ""})
