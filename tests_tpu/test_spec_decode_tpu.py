"""Real-chip lane for r13 draft-model speculative decoding.

The CPU tier-1 lane (tests/test_spec_decode.py) proves the mechanism on
the bucketed draft path; this lane proves the chip composition: the
DRAFT proposal loop rides the compiled-Mosaic ragged block-walk kernel
(decode_kernel auto picks ragged on TPU), the verify's bucketed gather
runs at real scale, and the headline numbers hold — exact greedy
parity vs the plain engine, > 1 committed token per verify with the
int8-quantized-target draft (the bench row's pairing), and a wall-clock
ordering sanity check.

    PADDLE_TPU_DEVICE_TESTS=1 python -m pytest tests_tpu/test_spec_decode_tpu.py -q
"""
import os
import time

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("PADDLE_TPU_DEVICE_TESTS") != "1",
    reason="real-device lane: set PADDLE_TPU_DEVICE_TESTS=1")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def _model():
    from paddle_tpu.models import llama
    cfg = llama.LlamaConfig(
        vocab_size=2048, hidden_size=512, intermediate_size=1024,
        num_layers=4, num_heads=8, num_kv_heads=8, head_dim=64,
        max_seq_len=1024, remat=False, dtype=jnp.bfloat16,
        use_flash=False)
    params = jax.jit(lambda k: jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16),
        llama.init_params(cfg, k)))(jax.random.PRNGKey(0))
    return cfg, params


def _run(params, cfg, prompts, n_new, **kw):
    from paddle_tpu.serving import LLMEngine
    eng = LLMEngine(params, cfg, max_slots=4, block_size=32,
                    max_model_len=512, prompt_buckets=[64, 256], **kw)
    rids = [eng.add_request(p, max_new_tokens=n)
            for p, n in zip(prompts, n_new)]
    out = eng.run()
    return [out[r] for r in rids], eng


def test_spec_parity_and_mechanism_on_chip():
    """int8-draft/bf16-target (the quant_matmul pairing): exact greedy
    stream parity vs the plain engine, acceptance high enough that the
    engine commits > 1 token per verify call, and the draft proposal
    dispatches rode the ragged kernel (decode_kernel auto on TPU)."""
    from paddle_tpu.models import llama
    cfg, params = _model()
    draft = jax.jit(llama.quantize_params)(params)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 2048, size=int(n)).tolist()
               for n in rng.integers(40, 250, size=8)]
    n_new = [48] * len(prompts)
    base, _ = _run(params, cfg, prompts, n_new)
    spec, eng = _run(params, cfg, prompts, n_new, draft_params=draft,
                     draft_config=cfg, spec_tokens=4)
    assert base == spec
    assert eng.spec_waves > 0
    assert eng.spec_committed / eng.spec_verify_calls > 1.0
    assert "ragged" in eng._spec_draft_cache
    assert len(eng._decode_cache) == 0       # every wave was speculative


def test_spec_variants_stay_bounded_on_chip():
    """The spec compile family: one draft variant per kernel path, one
    verify variant per power-of-two history bucket — the chunked-
    prefill axis, no new family."""
    cfg, params = _model()
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, 2048, size=int(n)).tolist()
               for n in (33, 180, 300, 64)]
    _, eng = _run(params, cfg, prompts, [40] * 4, draft_params=params,
                  draft_config=cfg, spec_tokens=4)
    assert set(eng._spec_draft_cache) == {"ragged"}
    assert all(nbk & (nbk - 1) == 0 for nbk in eng._spec_verify_cache)
    assert len(eng._spec_verify_cache) <= eng.mb.bit_length() + 1


def test_spec_throughput_ordering_on_chip():
    """Wall-clock sanity at acceptance ~1 (draft == quantized target):
    the speculative engine must not be SLOWER than the plain engine on
    the same greedy workload (the >= 1.5x acceptance number lands with
    the bench row on the serving-sized model; this guards the sign)."""
    from paddle_tpu.models import llama
    cfg, params = _model()
    draft = jax.jit(llama.quantize_params)(params)
    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, 2048, size=int(n)).tolist()
               for n in rng.integers(64, 200, size=8)]
    n_new = [64] * len(prompts)

    def timed(**kw):
        _run(params, cfg, prompts, n_new, **kw)      # warm/compile
        t0 = time.perf_counter()
        _run(params, cfg, prompts, n_new, **kw)
        return time.perf_counter() - t0

    t_plain = timed()
    t_spec = timed(draft_params=draft, draft_config=cfg, spec_tokens=4)
    assert t_spec <= 1.15 * t_plain, (t_spec, t_plain)
