#!/usr/bin/env python
"""Chaos run: seeded fault schedules against the training loop or the
serving engine, asserting recovery invariants.

Training mode (default; ``--train`` names it explicitly) — the CI-grade
end-to-end for distributed/resilience: the driver plays the role of the
elastic launcher — every SimulatedCrash kills the "process" (the
ResilientTrainLoop) and a fresh loop auto-resumes from the newest valid
checkpoint; after the first crash the newest checkpoint is deliberately
corrupted to exercise the fallback tier. A run passes when the faulted
job reaches the SAME final parameters (allclose), the same final eval
loss, and the same dataloader position as an uninterrupted run of equal
total steps. The schedule also carries a targeted ``nan_inject`` whose
rollback must carry NaN provenance: the numerics stats ladder
(observability.numerics) has to name EXACTLY the injected layer in the
rollback event and the flight-recorder post-mortem.

    JAX_PLATFORMS=cpu python tools/chaos_run.py --train --steps 12 --seed 7

Serving mode (``--serving``) — the same idea for the survivability
layer: a seeded schedule of readback crashes, pool squeezes, and slow
steps fires inside an LLMEngine loop while an over-capacity request
stream (some with unmeetable deadlines, half sharing a system-prompt
prefix) hits a bounded admission queue WITH the prefix cache and
chunked prefill on. A run passes when EVERY submitted request ends in
exactly one of {finished, shed, deadline_exceeded}, the block-pool
ledger balances ``free + backed + cached + squeezed + in_flight ==
total`` at every step boundary (zero KV block leaks — a pool_squeeze
stealing blocks while the cache holds others, or an r15 async spill
parking blocks behind an in-flight d2h, must still balance), the host
swap tier drains to empty, and the shared prefix actually hit the
cache. The schedule carries a seeded ``offload_crash`` — a crash fired
at the offload tick with transfers potentially in flight: recovery
must abandon them cleanly (reservations released, custody blocks
recycled, nothing half-committed). The r20 windowed shed-rate alert —
fed by the per-step time-series sampler — must FIRE during the storm
and CLEAR after the drain (one counted edge each way). A second
phase runs the r13 speculative engine (draft-then-verify waves) under
``spec_verify_fail`` faults: a crash between the verify dispatch and
its readback must roll back to the last committed token — the recovered
streams must equal a clean non-speculative greedy run token-for-token,
with the ledger balancing throughout (draft KV shares the target's
blocks, so the 4-term invariant is unchanged with spec on). A third
phase (r18) forces ``decode_kernel="mega"`` — the persistent fused
decode megakernel, running interpreted off-TPU — with the draft's
fused multi-step launch in play: a seeded readback crash lands
mid-wave, the 5-term ledger must balance at every step, and the
recovered streams must equal a clean forced-ragged run's
token-for-token.

    JAX_PLATFORMS=cpu python tools/chaos_run.py --serving --steps 24 --seed 7

HTTP mode (``--http``) — chaos at the NETWORK layer (r14): a real
HTTPFrontDoor (asyncio HTTP/1.1 + SSE over a ResilientEngine with
seeded readback crashes and pool squeezes) is driven by concurrent
stdlib-socket clients with seeded behaviors — mid-stream disconnects,
readers that never consume their stream, an offered-load burst at ~2x
slot capacity against a bounded admission queue, short client timeouts,
and a SIGTERM fired while streams are live (drain). A run passes when
every request id the engine minted ends in exactly one terminal reason
({finished, shed, deadline_exceeded, client_disconnected, drained}),
the 4-term block ledger balances at EVERY engine step (asserted from
the front door's step hook), completed SSE streams are exactly-once
(streamed frames == terminal frame token list), at least one shed and
one disconnect-cancel actually fired, the injected crash was recovered,
and after the drain there are zero live streams, zero backed blocks and
an empty swap tier.

    JAX_PLATFORMS=cpu python tools/chaos_run.py --http --requests 18 --seed 7

Router mode (``--router``) — kill-a-replica chaos (r16): a
ReplicaRouter fronts N in-process engine replicas on dedicated step
threads under a half-shared-prefix workload; a seeded victim replica is
killed MID-STREAM (its thread dies with slots occupied and tokens
already delivered). A run passes when every router-minted id ends in
exactly one terminal reason, every stream that finished — including the
failed-over ones resumed on a survivor from ``prompt + delivered`` — is
token-identical to an uninterrupted single-engine greedy run, the
per-replica block ledgers balance at every replica step (asserted from
the router's step hook), post-kill traffic lands only on survivors, the
revived victim rejoins through the half-open probe, and a full drain
leaves every replica's ledger clean. The r20 tok/s-divergence watcher
must FIRE for the victim on windowed evidence while it is down and
CLEAR after the drain.

The router run ends with a DISAGG phase (r19): a fresh 2-prefill +
2-decode fleet over one shared host relay takes the same offered load;
a seeded prefill replica is killed while it still owns streams (orphan
relay entries discarded, streams re-prefilled from the prompt), then a
seeded decode replica is killed mid-decode on relayed KV. Every stream
must finish token-identical to a clean COLOCATED single-engine run,
the per-replica ledgers balance at every step, and the relay pool
drains back to zero entries.

    JAX_PLATFORMS=cpu python tools/chaos_run.py --router --requests 12 --seed 7

Any failed run prints a one-line ``repro: chaos_run --<mode> --seed N
...`` command, so a red CI log hands you the exact seeded invocation.

Wired into the suite as tests/test_resilience.py::test_chaos_run_llama_parity,
tests/test_serving_resilience.py::test_chaos_run_serving,
tests/test_http_server.py::test_chaos_run_http and
tests/test_router.py::test_chaos_run_router
(slow lane: PADDLE_TPU_FULL_TESTS=1).
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _repro(args, mode):
    """The one-line reproduction command printed on any failed run —
    the seeded invocation itself, not a traceback to reverse-engineer."""
    parts = [f"repro: chaos_run --{mode}", f"--seed {args.seed}"]
    if mode == "router":
        parts.append(f"--replicas {args.replicas}")
    if mode in ("serving", "http", "router"):
        parts.append(f"--requests {args.requests}")
    if mode in ("train", "serving"):
        parts += [f"--steps {args.steps}", f"--rate {args.rate}"]
    return " ".join(parts)


def serving_main(args):
    import dataclasses
    import time

    import jax
    import jax.numpy as jnp

    import paddle_tpu.observability as obs
    from paddle_tpu.distributed.resilience import FaultInjector
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.models import llama
    from paddle_tpu.observability import timeseries
    from paddle_tpu.serving import (AdmissionConfig, LLMEngine,
                                    ResilientEngine, ShedError)

    cfg = dataclasses.replace(
        llama.tiny_llama(vocab=64, hidden=32, layers=2, heads=4, kv_heads=2,
                         seq=128, ffn=64),
        dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.PRNGKey(args.seed))

    # seeded random schedule over the serving fault menu, with the
    # canonical pair guaranteed: a readback crash and a pool squeeze
    inj = FaultInjector.random_schedule(
        seed=args.seed, n_steps=args.steps,
        kinds=("readback_fail", "pool_squeeze", "slow_step"),
        rate=args.rate)
    menu = [("readback_fail", max(2, args.steps // 3)),
            ("pool_squeeze", max(3, args.steps // 2)),
            # fired right after a squeeze so the preempt-swap it forces
            # is likely still in flight — the mid-transfer crash
            ("offload_crash", max(4, args.steps // 2 + 1))]
    inj = FaultInjector(inj.pending + menu)
    print(f"fault schedule: {inj.pending}")

    obs.enable()
    # r20 time-series sampler on the engine's own step tick: sample
    # every step and shrink the alert windows so the shed storm is
    # judged on windowed evidence inside this short seeded run
    set_flags({"obs_ts_interval_s": 0.0, "obs_ts_fast_window_s": 0.4,
               "obs_ts_slow_window_s": 1.0})
    timeseries.reset()
    # num_blocks=7 with two slots decoding 6-15 fresh tokens each: pool
    # pressure (and the injected squeezes) MUST preempt — the swap tier
    # is load-bearing in this run, not decorative. The r10 prefix cache
    # + chunked prefill run ON here: half the prompts share an 8-token
    # system prefix, so cache hits, refcount-0 evictions under squeeze,
    # and host spill/restore all fire inside the fault storm.
    eng = LLMEngine(params, cfg, max_slots=2, block_size=8,
                    max_model_len=64, num_blocks=7, prompt_buckets=[8, 32],
                    kv_swap_bytes=1 << 20,
                    admission=AdmissionConfig(max_queue=3),
                    injector=inj, prefix_cache=True, prefill_chunk=8,
                    prefix_cache_host_bytes=1 << 20)
    reng = ResilientEngine(eng)
    rng = np.random.default_rng(args.seed)
    shared = rng.integers(1, 64, size=8).tolist()

    all_ids, streamed = [], {}
    submitted = 0
    ok = True
    saw_inflight = False
    while eng.has_work() or submitted < args.requests:
        # offered load: up to two submissions per step (over capacity for
        # 2 slots), every 5th with a deadline that cannot be met, every
        # 2nd sharing the system prefix (the cache's food)
        for _ in range(2):
            if submitted >= args.requests:
                break
            submitted += 1
            kw = {"deadline_s": 0.0} if submitted % 5 == 0 else {}
            tail = rng.integers(1, 64,
                                size=int(rng.integers(3, 14))).tolist()
            prompt = shared + tail if submitted % 2 == 0 else tail
            try:
                rid = eng.add_request(
                    prompt, max_new_tokens=int(rng.integers(6, 16)), **kw)
                streamed[rid] = []
            except ShedError as e:
                rid = e.req_id
            all_ids.append(rid)
        for rid, tok in reng.step():
            streamed[rid].append(tok)
        acct = eng.block_accounting()
        if acct["free"] + acct["backed"] + acct["cached"] \
                + acct["squeezed"] + acct["in_flight"] != acct["total"]:
            print(f"block ledger out of balance at step "
                  f"{eng._step_idx}: {acct}")
            ok = False
            break
        saw_inflight = saw_inflight or acct["in_flight"] > 0

    eng.drain_offload()
    reasons = eng.finish_reasons
    counts = {}
    for r in reasons.values():
        counts[r] = counts.get(r, 0) + 1
    reg = obs.get_registry()
    pc = eng.prefix_cache
    print(f"serving chaos: {submitted} offered, {counts} | "
          f"recoveries={reng.recoveries} "
          f"swap_out={int(reg.counter('serving_kv_swap_out_total').labels().value)} "
          f"swap_in={int(reg.counter('serving_kv_swap_in_total').labels().value)} "
          f"faults fired={inj.fired}")
    print(f"prefix cache: hits={pc.hits} misses={pc.misses} "
          f"prefill_tokens_skipped={pc.tokens_skipped} "
          f"device_blocks={pc.device_blocks} host_blocks={pc.host_blocks}")
    off = eng.offload
    print(f"kv offload: sync={off.sync} saw_inflight={saw_inflight} "
          f"prefetch_hits={off.prefetch_hits} stalls={off.stalls} "
          f"stall_seconds={off.stall_seconds:.4f} "
          f"proactive_spills={off.proactive_spills}")

    terminal = {"finished", "shed", "deadline_exceeded"}
    if set(reasons) != set(all_ids):
        missing = set(all_ids) - set(reasons)
        print(f"requests without a terminal state: {sorted(missing)}")
        ok = False
    if not set(reasons.values()) <= terminal:
        print(f"non-terminal reasons: {set(reasons.values()) - terminal}")
        ok = False
    # exactly-once streaming for every request that was never crash-hit:
    # results must extend what was streamed (a recovered crash loses only
    # never-host-visible tokens)
    for rid, toks in streamed.items():
        if rid in eng.results and eng.results[rid][:len(toks)] != toks:
            print(f"request {rid}: stream/result mismatch")
            ok = False
    acct = eng.block_accounting()
    # drained: every block is free or parked in the (refcount-0) cache —
    # cached blocks are a feature at idle, backed/squeezed are leaks
    if not (acct["free"] + acct["cached"] == acct["total"]
            and acct["backed"] == 0 and acct["squeezed"] == 0
            and acct["swapped_host_blocks"] == 0):
        print(f"drained ledger not clean: {acct}")
        ok = False
    if any(nd.refcount for nd in pc._iter_nodes()):
        print("drained cache still holds pinned nodes")
        ok = False
    if eng.swap_pool.bytes_used != 0:
        print(f"host swap pool leaked {eng.swap_pool.bytes_used} bytes")
        ok = False
    if acct["in_flight"] != 0 or off.held_blocks != 0:
        print(f"drained engine still holds in-flight transfer blocks: "
              f"{acct['in_flight']}")
        ok = False
    if eng.swap_pool.reserved_bytes != 0 \
            or (pc.host is not None and pc.host.reserved_bytes != 0):
        print("host tier leaked async-spill reservations")
        ok = False
    if pc.hits < 1 or pc.tokens_skipped < 1:
        print(f"shared-prefix workload never hit the cache "
              f"(hits={pc.hits}, skipped={pc.tokens_skipped})")
        ok = False

    # r20 alert edges: the overload/pool_squeeze storm sheds requests,
    # and the windowed shed-rate watcher — fed by the per-step sampler
    # the engine itself drives — must FIRE while the storm is live,
    # then CLEAR once the engine drains and the fast window slides
    # past the last shed
    aeng = timeseries.get_alert_engine()
    shed_fired = aeng.edge_count("shed_rate", "firing")
    if shed_fired < 1:
        print("the shed storm never fired the shed_rate alert")
        ok = False
    deadline = time.monotonic() + 10
    while aeng.edge_count("shed_rate", "cleared") < 1 \
            and time.monotonic() < deadline:
        timeseries.tick()
        time.sleep(0.05)
    shed_cleared = aeng.edge_count("shed_rate", "cleared")
    print(f"alerts: shed_rate firing_edges={shed_fired} "
          f"cleared_edges={shed_cleared} "
          f"samples={len(timeseries.get_store())}")
    if shed_cleared < 1:
        print("the shed_rate alert never cleared after the drain")
        ok = False

    # -- phase 2 (r13): speculative chaos ---------------------------------
    # a fault injected MID-VERIFY (between the verify dispatch and its
    # readback) must roll the engine back to the last committed token:
    # the recovered run's streams must equal a clean non-speculative
    # run's token-for-token, and the block ledger must balance through
    # the crash + squeeze storm with the draft pools in play.
    spec_inj = FaultInjector([("spec_verify_fail", 2),
                              ("spec_verify_fail", 3),
                              ("spec_verify_fail", 7),
                              ("pool_squeeze", 5)])
    prompts = [rng.integers(1, 64, size=int(rng.integers(3, 14))).tolist()
               for _ in range(6)]
    news = [int(rng.integers(6, 16)) for _ in range(6)]
    ref = LLMEngine(params, cfg, max_slots=2, block_size=8,
                    max_model_len=64, prompt_buckets=[8, 32])
    ref_ids = [ref.add_request(p, max_new_tokens=n)
               for p, n in zip(prompts, news)]
    ref_out = ref.run()
    spec = LLMEngine(params, cfg, max_slots=2, block_size=8,
                     max_model_len=64, num_blocks=9,
                     prompt_buckets=[8, 32], kv_swap_bytes=1 << 20,
                     injector=spec_inj, draft_params=params,
                     draft_config=cfg, spec_tokens=4)
    rspec = ResilientEngine(spec)
    sids = [spec.add_request(p, max_new_tokens=n)
            for p, n in zip(prompts, news)]
    streamed2 = {rid: [] for rid in sids}
    while spec.has_work():
        for rid, tok in rspec.step():
            streamed2[rid].append(tok)
        acct = spec.block_accounting()
        if acct["free"] + acct["backed"] + acct["cached"] \
                + acct["squeezed"] + acct["in_flight"] != acct["total"]:
            print(f"spec ledger out of balance at step "
                  f"{spec._step_idx}: {acct}")
            ok = False
            break
    print(f"spec chaos: recoveries={rspec.recoveries} "
          f"waves={spec.spec_waves} committed={spec.spec_committed} "
          f"accepted={spec.spec_accepted}/{spec.spec_proposed} "
          f"faults fired={spec_inj.fired}")
    if rspec.recoveries < 1:
        print("no mid-verify crash was recovered — the fault never fired")
        ok = False
    for rid, refid in zip(sids, ref_ids):
        if spec.results.get(rid) != ref_out[refid]:
            print(f"spec request {rid} diverged from the clean greedy "
                  f"stream: {spec.results.get(rid)} != {ref_out[refid]}")
            ok = False
        if streamed2[rid] != spec.results.get(rid):
            print(f"spec request {rid}: streamed/result mismatch")
            ok = False

    # -- phase 3 (r18): megakernel chaos ----------------------------------
    # the fused decode path under fire: decode_kernel="mega" forced on
    # (the Pallas megakernel runs interpreted off-TPU), the draft's
    # fused multi-step launch in play, and seeded readback crashes
    # timed to land mid-wave (spec_verify_fail raises at the wave's
    # blocking readback sync). Recovery must roll back to the last
    # committed token, the 5-term block ledger must balance at every
    # step, and the recovered streams must equal a clean forced-ragged
    # run's token-for-token (the acceptance parity, under faults).
    mega_inj = FaultInjector([("spec_verify_fail", 2),
                              ("spec_verify_fail", 4),
                              ("pool_squeeze", 6)])
    prompts = [rng.integers(1, 64, size=int(rng.integers(3, 14))).tolist()
               for _ in range(4)]
    news = [int(rng.integers(6, 16)) for _ in range(4)]
    rag = LLMEngine(params, cfg, max_slots=2, block_size=8,
                    max_model_len=64, prompt_buckets=[8, 32],
                    decode_kernel="ragged", draft_params=params,
                    draft_config=cfg, spec_tokens=3)
    rag_ids = [rag.add_request(p, max_new_tokens=n)
               for p, n in zip(prompts, news)]
    rag_out = rag.run()
    mega = LLMEngine(params, cfg, max_slots=2, block_size=8,
                     max_model_len=64, num_blocks=9,
                     prompt_buckets=[8, 32], kv_swap_bytes=1 << 20,
                     injector=mega_inj, decode_kernel="mega",
                     draft_params=params, draft_config=cfg,
                     spec_tokens=3)
    rmega = ResilientEngine(mega)
    mids = [mega.add_request(p, max_new_tokens=n)
            for p, n in zip(prompts, news)]
    streamed3 = {rid: [] for rid in mids}
    while mega.has_work():
        for rid, tok in rmega.step():
            streamed3[rid].append(tok)
        acct = mega.block_accounting()
        if acct["free"] + acct["backed"] + acct["cached"] \
                + acct["squeezed"] + acct["in_flight"] != acct["total"]:
            print(f"mega ledger out of balance at step "
                  f"{mega._step_idx}: {acct}")
            ok = False
            break
    print(f"mega chaos: recoveries={rmega.recoveries} "
          f"waves={mega.spec_waves} committed={mega.spec_committed} "
          f"faults fired={mega_inj.fired}")
    if rmega.recoveries < 1:
        print("no mid-wave crash was recovered — the fault never fired")
        ok = False
    if not all(k[0] == "mega" for k in mega._decode_cache):
        print(f"forced mega engine compiled non-mega variants: "
              f"{sorted(mega._decode_cache)}")
        ok = False
    if "mega" not in mega._spec_draft_cache:
        print("the fused multi-step draft launch never compiled")
        ok = False
    for rid, refid in zip(mids, rag_ids):
        if mega.results.get(rid) != rag_out[refid]:
            print(f"mega request {rid} diverged from the clean ragged "
                  f"stream: {mega.results.get(rid)} != {rag_out[refid]}")
            ok = False
        if streamed3[rid] != mega.results.get(rid):
            print(f"mega request {rid}: streamed/result mismatch")
            ok = False

    if not ok:
        print(_repro(args, "serving"))
    print("SERVING_CHAOS: OK" if ok else "SERVING_CHAOS: FAIL")
    return 0 if ok else 1


def http_main(args):
    """Network-layer chaos: seeded client misbehavior against a live
    HTTPFrontDoor, engine invariants asserted from the socket inward."""
    import dataclasses
    import json
    import signal
    import socket
    import threading
    import time

    import jax
    import jax.numpy as jnp

    import paddle_tpu.observability as obs
    from paddle_tpu.distributed.resilience import FaultInjector
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.models import llama
    from paddle_tpu.serving import (AdmissionConfig, HTTPFrontDoor,
                                    LLMEngine, ResilientEngine)

    obs.enable()
    set_flags({"serve_drain_s": 20.0})
    cfg = dataclasses.replace(
        llama.tiny_llama(vocab=64, hidden=32, layers=2, heads=4, kv_heads=2,
                         seq=128, ffn=64),
        dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.PRNGKey(args.seed))
    eng = LLMEngine(params, cfg, max_slots=2, block_size=8,
                    max_model_len=64, num_blocks=9, prompt_buckets=[8, 32],
                    kv_swap_bytes=1 << 20,
                    admission=AdmissionConfig(max_queue=3))
    # warm the compile caches BEFORE opening traffic (threads not
    # started yet, so driving the engine here is safe): cold-start
    # compilation would otherwise stall the first burst for seconds and
    # turn the whole offered load into queue_full sheds — chaos should
    # exercise a SERVING engine, not a compiling one
    wrng = np.random.default_rng(args.seed)
    for _ in range(2):
        eng.add_request(wrng.integers(1, 64, size=6).tolist(),
                        max_new_tokens=4)
    eng.run()
    # the injector arms only now, with steps keyed past the warmup:
    # readback crashes timed to hit live streams (retrying comment
    # frames + recovery), one squeeze for pool pressure
    base = eng._step_idx
    inj = FaultInjector([("readback_fail", base + 4),
                         ("readback_fail", base + 12),
                         ("pool_squeeze", base + 8)])
    eng.injector = inj
    reng = ResilientEngine(eng)

    violations = []

    def ledger_hook(e):
        acct = e.block_accounting()
        if acct["free"] + acct["backed"] + acct["cached"] \
                + acct["squeezed"] + acct.get("in_flight", 0) \
                != acct["total"]:
            violations.append((e._step_idx, acct))

    front = HTTPFrontDoor(reng, step_hook=ledger_hook)
    host, port = front.start()
    # SIGTERM mid-stream = the orchestrator's restart signal: drain
    signal.signal(signal.SIGTERM, lambda *_a: front.begin_drain())

    rng = np.random.default_rng(args.seed)
    records = []
    rec_lock = threading.Lock()

    def draw_workload(behavior):
        # drawn on the MAIN thread only: numpy Generators are not
        # thread-safe, and same-seed reruns must offer the same
        # prompts whatever the client-thread scheduling
        doc = {"prompt": rng.integers(
                   1, 64, size=int(rng.integers(3, 12))).tolist(),
               "max_new_tokens": int(rng.integers(8, 20))}
        if behavior == "deadline":
            doc["timeout_s"] = 0.05
        return doc

    def run_client(i, behavior, doc):
        rec = {"i": i, "behavior": behavior, "code": None,
               "streamed": [], "terminal": None, "reason": None}
        try:
            body = json.dumps(doc).encode()
            s = socket.create_connection((host, port), timeout=30)
            s.sendall((f"POST /v1/generate HTTP/1.1\r\nHost: x\r\n"
                       f"Content-Length: {len(body)}\r\n"
                       f"X-Tenant: t{i % 3}\r\n\r\n").encode() + body)
            buf = b""
            while b"\r\n\r\n" not in buf:
                c = s.recv(4096)
                if not c:
                    break
                buf += c
            rec["code"] = int(buf.split(b" ", 2)[1]) if buf else None
            if rec["code"] != 200:
                s.close()
                return
            if behavior == "disconnect":
                # slam the connection after the first token frame: the
                # server must cancel the request and free its blocks
                while buf.count(b"data:") < 1:
                    c = s.recv(1)
                    if not c:
                        break
                    buf += c
                s.close()
                return
            if behavior == "stall":
                # never consume the stream: the server must not wedge
                # (tiny streams fit the kernel buffers, so the engine
                # finishes the request; the stall-cancel sweep itself
                # is white-box-tested — tests/test_http_server.py)
                time.sleep(0.6)
                s.close()
                return
            while True:                    # normal / deadline readers
                c = s.recv(65536)
                if not c:
                    break
                buf += c
            s.close()
            for chunk in buf.split(b"data: ")[1:]:
                payload = chunk.split(b"\n", 1)[0]
                obj = json.loads(payload)
                if "token" in obj:
                    rec["streamed"].append(obj["token"])
                elif obj.get("done"):
                    rec["terminal"] = obj["tokens"]
                    rec["reason"] = obj["reason"]
        except (OSError, ValueError) as e:
            rec.setdefault("error", repr(e))
        finally:
            with rec_lock:
                records.append(rec)

    # seeded behavior mix; bursts of 6 concurrent clients offer ~2x the
    # 2-slot + 3-queue capacity, so the bounded queue MUST shed
    behaviors = []
    for i in range(args.requests):
        r = rng.random()
        behaviors.append("disconnect" if r < 0.2 else
                         "stall" if r < 0.35 else
                         "deadline" if r < 0.5 else "normal")
    workloads = [draw_workload(b) for b in behaviors]
    late_doc = draw_workload("normal")
    threads = []
    for burst_start in range(0, len(behaviors), 6):
        burst = behaviors[burst_start:burst_start + 6]
        for j, b in enumerate(burst):
            t = threading.Thread(
                target=run_client,
                args=(burst_start + j, b, workloads[burst_start + j]))
            t.start()
            threads.append(t)
        time.sleep(0.4)
    # SIGTERM while the last burst's streams are in flight: drain must
    # let them finish and 503 every later arrival
    os.kill(os.getpid(), signal.SIGTERM)
    late = threading.Thread(target=run_client,
                            args=(len(behaviors), "normal", late_doc))
    late.start()
    threads.append(late)
    for t in threads:
        t.join(60)
    ok = front.wait_drained(30)
    front.stop()

    reasons = dict(eng.finish_reasons)
    counts = {}
    for r in reasons.values():
        counts[r] = counts.get(r, 0) + 1
    codes = {}
    for rec in records:
        codes[rec["code"]] = codes.get(rec["code"], 0) + 1
    reg = obs.get_registry()
    disconnects = int(reg.counter(
        "serving_http_client_disconnects_total").labels().value)
    print(f"http chaos: {args.requests} clients {codes} | terminal "
          f"{counts} | recoveries={reng.recoveries} "
          f"disconnect_cancels={disconnects} faults fired={inj.fired}")

    if not ok:
        print("drain never completed")
    terminal = {"finished", "shed", "deadline_exceeded",
                "client_disconnected", "drained"}
    minted = set(range(eng._next_id))
    if set(reasons) != minted:
        print(f"requests without a terminal state: "
              f"{sorted(minted - set(reasons))}")
        ok = False
    if not set(reasons.values()) <= terminal:
        print(f"non-terminal reasons: {set(reasons.values()) - terminal}")
        ok = False
    if violations:
        print(f"block ledger violations: {violations[:3]}")
        ok = False
    for rec in records:
        if rec["behavior"] in ("normal", "deadline") \
                and rec["terminal"] is not None \
                and rec["reason"] == "finished" \
                and rec["streamed"] != rec["terminal"]:
            print(f"client {rec['i']}: streamed/terminal mismatch "
                  f"{rec['streamed']} != {rec['terminal']}")
            ok = False
    eng.drain_offload()
    acct = eng.block_accounting()
    if not (acct["free"] + acct["cached"] == acct["total"]
            and acct["backed"] == 0 and acct["squeezed"] == 0
            and acct["swapped_host_blocks"] == 0):
        print(f"drained ledger not clean: {acct}")
        ok = False
    if front.active_streams != 0:
        print(f"{front.active_streams} streams survived the drain")
        ok = False
    if eng.swap_pool.bytes_used != 0:
        print(f"host swap pool leaked {eng.swap_pool.bytes_used} bytes")
        ok = False
    if acct["in_flight"] != 0 or eng.offload.held_blocks != 0 \
            or eng.swap_pool.reserved_bytes != 0:
        print("drained front-door engine still holds in-flight "
              "transfer state")
        ok = False
    if counts.get("shed", 0) < 1:
        print("the 2x overload burst never hit the bounded queue")
        ok = False
    draining_503 = any(rec["code"] == 503 for rec in records
                       if rec["i"] >= len(behaviors))
    if not draining_503:
        print("the post-SIGTERM arrival was not refused with 503")
        ok = False
    if disconnects < 1:
        print("no disconnect was cancelled server-side")
        ok = False
    if reng.recoveries < 1:
        print("the injected readback crash never fired/recovered")
        ok = False

    if not ok:
        print(_repro(args, "http"))
    print("HTTP_CHAOS: OK" if ok else "HTTP_CHAOS: FAIL")
    return 0 if ok else 1


def router_main(args):
    """Kill-a-replica chaos: a seeded mid-stream replica death under a
    ReplicaRouter, exactly-once resume parity asserted against a clean
    single-engine run."""
    import dataclasses
    import time

    import jax
    import jax.numpy as jnp

    import paddle_tpu.observability as obs
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.models import llama
    from paddle_tpu.observability import fleet
    from paddle_tpu.observability import timeseries
    from paddle_tpu.serving import LLMEngine, ReplicaRouter

    obs.enable()
    # r20 time-series sampler: every health tick / engine step samples,
    # and the divergence watcher judges the kill on a window short
    # enough to resolve inside this seeded run
    set_flags({"obs_ts_interval_s": 0.0, "obs_ts_fast_window_s": 0.5,
               "obs_ts_slow_window_s": 2.0})
    timeseries.reset()
    cfg = dataclasses.replace(
        llama.tiny_llama(vocab=64, hidden=32, layers=2, heads=4, kv_heads=2,
                         seq=128, ffn=64),
        dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.PRNGKey(args.seed))

    def mk_engine():
        return LLMEngine(params, cfg, max_slots=2, block_size=8,
                         max_model_len=64, prompt_buckets=[8, 48])

    engines = [mk_engine() for _ in range(args.replicas)]
    # warm every replica's compile caches BEFORE the step threads exist
    # (both prefill buckets + the decode wave): a cold first step takes
    # seconds and would let wall-clock health timers mistake compilation
    # for death — chaos should kill a SERVING replica, not a compiling one
    wrng = np.random.default_rng(args.seed)
    for eng in engines:
        eng.add_request(wrng.integers(1, 64, size=6).tolist(),
                        max_new_tokens=4)
        eng.add_request(wrng.integers(1, 64, size=20).tolist(),
                        max_new_tokens=4)
        eng.run()

    violations = []

    def ledger_hook(name, eng):
        acct = eng.block_accounting()
        if acct["free"] + acct["backed"] + acct["cached"] \
                + acct["squeezed"] + acct.get("in_flight", 0) \
                != acct["total"]:
            violations.append((name, eng._step_idx, acct))

    names = [f"r{i}" for i in range(args.replicas)]
    # generous wall-clock thresholds: this run drives death/revival
    # explicitly (kill_replica/revive_replica), and a CI box under load
    # must not see a slow-but-alive replica declared dead on its own
    router = ReplicaRouter(engines, names=names, step_hook=ledger_hook,
                           suspect_s=15.0, dead_s=30.0, halfopen_s=0.2)
    router.start()

    # r17 counter conservation: at EVERY health tick, for every counter
    # in the merged fleet snapshot, the fleet-aggregated value must
    # equal the sum over the per-replica scoped series OF THE SAME
    # snapshot set (one atomic registry read per tick — comparing
    # against a later live read would race in-flight increments)
    import math

    agg = fleet.get_aggregator()
    conservation_failures = []
    conservation_ticks = [0]

    def _counter_sums(snaps):
        sums = {}
        for snap in snaps.values():
            for fam in snap.get("metrics", []):
                if fam["kind"] != "counter":
                    continue
                for s in fam.get("series", []):
                    labels = {k: v for k, v
                              in s.get("labels", {}).items()
                              if k != "replica"}
                    key = (fam["name"], tuple(sorted(labels.items())))
                    sums[key] = sums.get(key, 0.0) \
                        + float(s.get("value", 0.0))
        return sums

    def conservation_tick():
        conservation_ticks[0] += 1
        snaps = agg.snapshots()
        merged = fleet.merge_snapshots(snaps)
        expect = _counter_sums(snaps)
        got = {}
        for fam in merged["metrics"]:
            if fam["kind"] != "counter":
                continue
            for s in fam["series"]:
                key = (fam["name"], tuple(sorted(s["labels"].items())))
                got[key] = float(s["value"])
        bad = {k: (got.get(k), expect.get(k))
               for k in set(got) | set(expect)
               if not math.isclose(got.get(k, 0.0), expect.get(k, 0.0),
                                   rel_tol=1e-9, abs_tol=1e-12)}
        if bad and len(conservation_failures) < 3:
            conservation_failures.append(bad)

    def wait_ticking(rids, timeout=120.0):
        """Wait for every rid, calling a health tick + the conservation
        check every ~25ms — the check runs DURING the kill/failover
        window, not just at quiescence."""
        deadline = time.monotonic() + timeout
        pending = list(rids)
        while pending and time.monotonic() < deadline:
            pending = [rid for rid in pending
                       if not router._streams[rid].done.is_set()]
            router.check()
            conservation_tick()
            time.sleep(0.025)
        for rid in rids:
            router.wait(rid, timeout=max(0.0,
                                         deadline - time.monotonic()))

    # seeded workload: half the prompts share an 8-token system prefix
    # (the affinity scorer's food), long-ish decodes so the kill lands
    # mid-stream; prompt(<=20) + delivered(<16) stays inside bucket 48
    rng = np.random.default_rng(args.seed)
    shared = rng.integers(1, 64, size=8).tolist()
    workload = []
    for i in range(args.requests):
        tail = rng.integers(1, 64, size=int(rng.integers(3, 12))).tolist()
        prompt = shared + tail if i % 2 == 0 else tail
        workload.append((prompt, int(rng.integers(8, 16))))

    ok = True
    first = workload[:max(2, args.requests // 2)]
    rest = workload[len(first):]
    rids = [router.submit(p, max_new_tokens=n) for p, n in first]

    # wait for a mid-stream moment: some replica owns a stream that has
    # already delivered tokens but is not finished
    victim = None
    deadline = time.monotonic() + 30
    while victim is None and time.monotonic() < deadline:
        with router._lock:
            live = [rec for rec in router._streams.values()
                    if rec.replica is not None and not rec.done.is_set()
                    and len(rec.delivered) >= 2]
            if live:
                # seeded victim choice among replicas with live streams
                owners = sorted({rec.replica for rec in live})
                victim = owners[int(rng.integers(0, len(owners)))]
        time.sleep(0.002)
    if victim is None:
        print("no stream was ever mid-flight — workload too small")
        ok = False
        victim = names[0]
    pre_kill = {n: rep.dispatches for n, rep in router.replicas.items()}
    print(f"killing {victim} mid-stream "
          f"(dispatches so far: {pre_kill})")
    router.kill_replica(victim)

    # post-kill offered load must land on survivors only; the wait runs
    # health ticks + the conservation check straight through the kill
    rids += [router.submit(p, max_new_tokens=n) for p, n in rest]
    wait_ticking(rids, timeout=120)

    reasons = dict(router.finish_reasons)
    counts = {}
    for r in reasons.values():
        counts[r] = counts.get(r, 0) + 1
    print(f"router chaos: {len(rids)} offered, {counts} | "
          f"failovers={router.failovers} resumed={router.resumed_streams} "
          f"affinity={router.affinity_hits}/{router.affinity_misses} "
          f"dedup_drops={router.dedup_drops} sheds={router.router_sheds}")

    # every minted id: exactly one terminal reason, from the closed set
    terminal = {"finished", "shed", "deadline_exceeded",
                "client_disconnected", "drained"}
    if set(reasons) != set(rids):
        print(f"requests without a terminal state: "
              f"{sorted(set(rids) - set(reasons))}")
        ok = False
    if not set(reasons.values()) <= terminal:
        print(f"non-terminal reasons: {set(reasons.values()) - terminal}")
        ok = False
    if router.failovers < 1 or router.resumed_streams < 1:
        print("the kill never orphaned a live stream — nothing failed over")
        ok = False
    if router.affinity_hits < 1:
        print("shared-prefix workload never scored an affinity hit")
        ok = False

    # r20 alert edge: the dead victim's token counter froze while the
    # survivors kept decoding — the tok/s-divergence watcher must fire
    # FOR THE VICTIM on windowed evidence. Paired keep-alive traffic
    # holds both survivors' rates (and so the fleet median) above the
    # watcher's floor until the fast window slides fully past the kill.
    aeng = timeseries.get_alert_engine()

    def _victim_diverged():
        return any(r["alert"] == "replica_tok_s_divergence"
                   and r["instance"] == victim for r in aeng.firing())

    deadline = time.monotonic() + 20
    while not _victim_diverged() and time.monotonic() < deadline:
        kas = [router.submit(rng.integers(1, 64, size=4).tolist(),
                             max_new_tokens=6) for _ in range(2)]
        for ka in kas:
            router.wait(ka, timeout=30)
        router.check()
    div_fired = aeng.edge_count("replica_tok_s_divergence", "firing")
    print(f"alerts: tok/s divergence firing_edges={div_fired} "
          f"victim_firing={_victim_diverged()} "
          f"samples={len(timeseries.get_store())}")
    if not _victim_diverged():
        print(f"the kill never fired the tok/s-divergence alert for "
              f"{victim}")
        ok = False

    # exactly-once resume parity: EVERY finished stream — resumed or
    # not — must be token-identical to an uninterrupted single-engine
    # greedy run of the same workload
    ref = mk_engine()
    ref_ids = [ref.add_request(p, max_new_tokens=n) for p, n in workload]
    ref_out = ref.run()
    for rid, refid in zip(rids, ref_ids):
        if reasons.get(rid) != "finished":
            continue
        if router.results[rid] != ref_out[refid]:
            print(f"request {rid} diverged from the clean greedy run: "
                  f"{router.results[rid]} != {ref_out[refid]}")
            ok = False

    # r17 fleet conservation verdict: the per-tick merge-vs-sum checks
    # ran through the kill window, plus one quiescent check against the
    # live registry now that streams are terminal
    conservation_tick()
    print(f"fleet conservation: {conservation_ticks[0]} ticks, "
          f"{len(conservation_failures)} violation(s)")
    if conservation_failures:
        print(f"counter conservation violated: "
              f"{conservation_failures[0]}")
        ok = False
    if conservation_ticks[0] < 3:
        print("too few conservation ticks — the check never ran "
              "through the kill window")
        ok = False

    # r17 failover-continuous traces: every resumed stream keeps ONE
    # timeline — reachable under its new engine rid AND the old one
    # (alias), carrying a structured failover hop with the delivered
    # count, its summary totals spanning both legs
    tracer = obs.request_trace.get_request_tracer()
    resumed_recs = [rec for rec in router._streams.values()
                    if rec.resumes >= 1 and not rec.cancelled
                    and reasons.get(rec.rid) == "finished"]
    if not resumed_recs:
        print("no resumed stream finished — trace continuity unchecked")
        ok = False
    for rec in resumed_recs:
        doc = tracer.get(rec.engine_rid)
        if doc is None:
            print(f"resumed stream {rec.rid}: no timeline under engine "
                  f"rid {rec.engine_rid}")
            ok = False
            continue
        kinds = [ev["kind"] for ev in doc["events"]]
        hops = [ev for ev in doc["events"] if ev["kind"] == "failover"]
        if not hops:
            print(f"resumed stream {rec.rid}: timeline has no failover "
                  f"hop: {kinds}")
            ok = False
            continue
        hop = hops[0]
        if hop.get("to") != rec.replica or "from" not in hop \
                or "delivered" not in hop:
            print(f"resumed stream {rec.rid}: malformed failover hop "
                  f"{hop}")
            ok = False
        if doc.get("summary", {}).get("failovers", 0) < rec.resumes:
            print(f"resumed stream {rec.rid}: summary counts "
                  f"{doc.get('summary', {}).get('failovers')} failovers,"
                  f" router counts {rec.resumes}")
            ok = False
        if doc.get("summary", {}).get("tokens") != len(rec.delivered):
            print(f"resumed stream {rec.rid}: grafted summary tokens "
                  f"{doc.get('summary', {}).get('tokens')} != delivered "
                  f"{len(rec.delivered)}")
            ok = False

    # exemplars stay valid through the kill: the p99 TTFT exemplar must
    # resolve to a request the (grafted) tracer still knows
    reg = obs.get_registry()
    ex = obs.exemplar_for_quantile(
        reg.histogram("serving_ttft_seconds"), 0.99)
    if ex is None:
        print("no TTFT p99 exemplar after the chaos run")
        ok = False
    elif tracer.get(ex["request_id"]) is None:
        print(f"TTFT p99 exemplar points at unknown request "
              f"{ex['request_id']}")
        ok = False

    # rebalance: the dead victim took no post-kill dispatches; every
    # survivor kept serving
    post_kill = {n: rep.dispatches for n, rep in router.replicas.items()}
    if post_kill[victim] != pre_kill[victim]:
        print(f"dead replica {victim} was dispatched to after the kill: "
              f"{pre_kill[victim]} -> {post_kill[victim]}")
        ok = False
    survivors = [n for n in names if n != victim]
    if rest and not any(post_kill[n] > pre_kill[n] for n in survivors):
        print(f"post-kill traffic never landed on a survivor: "
              f"{pre_kill} -> {post_kill}")
        ok = False

    # circuit breaker: the revived victim rejoins through the half-open
    # probe under fresh traffic, never by fiat
    router.revive_replica(victim)
    router.check()
    if router.states()[victim] not in ("dead", "half_open"):
        print(f"revived {victim} skipped the circuit breaker: "
              f"{router.states()[victim]}")
        ok = False
    probe_rids = []
    deadline = time.monotonic() + 30
    while router.states()[victim] != "healthy" \
            and time.monotonic() < deadline:
        router.check()
        probe_rids.append(router.submit(
            rng.integers(1, 64, size=4).tolist(), max_new_tokens=4))
        for rid in probe_rids[-1:]:
            router.wait(rid, timeout=60)
    router.check()
    if router.states()[victim] != "healthy":
        print(f"revived {victim} never closed the circuit: "
              f"{router.states()}")
        ok = False

    # full drain: every replica's ledger clean, no stream left behind
    if not router.drain_all(timeout=60):
        print("drain never completed")
        ok = False
    for name, rep in router.replicas.items():
        acct = rep.raw.block_accounting()
        if not (acct["free"] + acct["cached"] == acct["total"]
                and acct["backed"] == 0 and acct["squeezed"] == 0):
            print(f"replica {name} drained ledger not clean: {acct}")
            ok = False
    if router.live_streams():
        print(f"streams survived the drain: {router.live_streams()}")
        ok = False
    if violations:
        print(f"per-replica ledger violations: {violations[:3]}")
        ok = False
    noops = sum(rep.raw.cancel_noops for rep in router.replicas.values())
    print(f"post-drain states: {router.states()} | "
          f"cancel_noops={noops} ledger_checks_per_replica="
          f"{ {n: rep.steps for n, rep in router.replicas.items()} }")

    # r20 cleared edge: with the fleet drained every replica's token
    # rate decays to zero, the median falls below the watcher's floor,
    # and the divergence alert must CLEAR (one cleared edge per
    # transition — the revived victim must not stay marked diverged)
    deadline = time.monotonic() + 10
    while (_victim_diverged()
           or aeng.edge_count("replica_tok_s_divergence",
                              "cleared") < 1) \
            and time.monotonic() < deadline:
        timeseries.tick()
        time.sleep(0.05)
    div_cleared = aeng.edge_count("replica_tok_s_divergence", "cleared")
    print(f"alerts: tok/s divergence cleared_edges={div_cleared}")
    if _victim_diverged() or div_cleared < 1:
        print("the tok/s-divergence alert never cleared after the drain")
        ok = False
    router.stop()

    # ---- disaggregated prefill/decode phase (r19) -------------------------
    # A fresh 4-replica fleet: 2 prefill-role + 2 decode-role replicas
    # over ONE shared host relay. Two seeded kills: a prefill replica
    # while it still owns streams (some may sit spilled in the relay,
    # unobserved by the router — those entries must be discarded, the
    # streams re-prefilled from the prompt), then a decode replica
    # mid-decode on relayed KV (failover re-prefills prompt+delivered).
    # Asserted: every stream finishes exactly once, token-identical to
    # a clean COLOCATED single-engine greedy run; per-replica 5-term
    # ledgers balance at every step; the relay pool drains to zero.
    from paddle_tpu.serving.kv_swap import HostKVPool

    print()
    drng = np.random.default_rng(args.seed + 1)
    relay = HostKVPool(1 << 30, kind="relay")

    def mk_role(role):
        return LLMEngine(params, cfg, max_slots=2, block_size=8,
                         max_model_len=64, prompt_buckets=[8, 48],
                         role=role, relay=relay)

    droles = {"p0": "prefill", "p1": "prefill",
              "d0": "decode", "d1": "decode"}
    d_engines = {n: mk_role(r) for n, r in droles.items()}
    # warm compile caches before the step threads exist; a prefill-role
    # warmup hands its KV off — drop those entries, they have no
    # consumer
    for eng in d_engines.values():
        w1 = eng.add_request(wrng.integers(1, 64, size=6).tolist(),
                             max_new_tokens=4)
        w2 = eng.add_request(wrng.integers(1, 64, size=20).tolist(),
                             max_new_tokens=4)
        eng.run()
        relay.discard(w1)
        relay.discard(w2)
    if len(relay):
        print(f"warmup left {len(relay)} relay entries behind")
        ok = False

    d_violations = []

    def d_ledger_hook(name, eng):
        acct = eng.block_accounting()
        if acct["free"] + acct["backed"] + acct["cached"] \
                + acct["squeezed"] + acct.get("in_flight", 0) \
                != acct["total"]:
            d_violations.append((name, eng._step_idx, acct))

    drouter = ReplicaRouter(list(d_engines.values()),
                            names=list(d_engines),
                            step_hook=d_ledger_hook,
                            suspect_s=15.0, dead_s=30.0, halfopen_s=0.2)
    drouter.start()

    dworkload = []
    for _ in range(args.requests):
        prompt = drng.integers(
            1, 64, size=int(drng.integers(4, 12))).tolist()
        dworkload.append((prompt, int(drng.integers(8, 16))))
    dfirst = dworkload[:max(2, args.requests // 2)]
    drest = dworkload[len(dfirst):]
    drids = [drouter.submit(list(p), max_new_tokens=n)
             for p, n in dfirst]

    # seeded prefill-replica kill: the handoff machinery must be LIVE
    # (>= 1 spill already happened) and the victim must still own
    # streams — those die before their own handoff and re-prefill
    p_victim = None
    deadline = time.monotonic() + 30
    while p_victim is None and time.monotonic() < deadline:
        with drouter._lock:
            owners = sorted(n for n, rep in drouter.replicas.items()
                            if droles[n] == "prefill" and rep.owned)
        spilled = sum(d_engines[n].handoffs for n, r in droles.items()
                      if r == "prefill")
        if spilled >= 1 and owners:
            p_victim = owners[int(drng.integers(0, len(owners)))]
        time.sleep(0.001)
    if p_victim is None:
        print("no prefill replica ever owned a stream post-handoff")
        ok = False
        p_victim = "p0"
    print(f"disagg: killing prefill replica {p_victim} mid-handoff "
          f"(handoffs so far: "
          f"{ {n: d_engines[n].handoffs for n in ('p0', 'p1')} })")
    drouter.kill_replica(p_victim)

    drids += [drouter.submit(list(p), max_new_tokens=n)
              for p, n in drest]

    # seeded decode-replica kill: a stream must be decoding ON relayed
    # KV (owner is a decode replica, >= 2 tokens out — the handoff
    # token plus at least one decoded there)
    d_victim = None
    deadline = time.monotonic() + 30
    while d_victim is None and time.monotonic() < deadline:
        with drouter._lock:
            live = sorted({rec.replica
                           for rec in drouter._streams.values()
                           if rec.replica in ("d0", "d1")
                           and not rec.done.is_set()
                           and len(rec.delivered) >= 2})
        if live:
            d_victim = live[int(drng.integers(0, len(live)))]
        time.sleep(0.001)
    if d_victim is None:
        print("no stream was ever mid-decode on a decode replica")
        ok = False
        d_victim = "d0"
    print(f"disagg: killing decode replica {d_victim} post-handoff")
    drouter.kill_replica(d_victim)

    deadline = time.monotonic() + 120
    pending = list(drids)
    while pending and time.monotonic() < deadline:
        pending = [rid for rid in pending
                   if not drouter._streams[rid].done.is_set()]
        drouter.check()
        time.sleep(0.02)
    for rid in drids:
        drouter.wait(rid, timeout=max(0.0,
                                      deadline - time.monotonic()))

    dreasons = {rid: drouter.finish_reasons.get(rid) for rid in drids}
    dcounts = {}
    for r in dreasons.values():
        dcounts[r] = dcounts.get(r, 0) + 1
    total_handoffs = sum(e.handoffs for e in d_engines.values())
    print(f"disagg chaos: {len(drids)} offered, {dcounts} | "
          f"handoffs={total_handoffs} "
          f"handoff_resumes={drouter.handoff_resumes} "
          f"failovers={drouter.failovers} "
          f"resumed={drouter.resumed_streams} relay_len={len(relay)}")

    # exactly-once, and in THIS phase (no overload, no cancels, two
    # survivors) every stream must land in "finished"
    if any(dreasons.get(rid) != "finished" for rid in drids):
        print(f"disagg streams not all finished: {dcounts}")
        ok = False
    if total_handoffs < 1 or drouter.handoff_resumes < 1:
        print("the disagg fleet never handed a stream off")
        ok = False
    if drouter.failovers < 1:
        print("neither kill orphaned a live stream")
        ok = False

    # greedy parity: disagg + two kills must equal a clean COLOCATED
    # single-engine run of the same workload, token for token
    dref = mk_engine()
    dref_ids = [dref.add_request(list(p), max_new_tokens=n)
                for p, n in dworkload]
    dref_out = dref.run()
    for rid, refid in zip(drids, dref_ids):
        if dreasons.get(rid) != "finished":
            continue
        if drouter.results[rid] != dref_out[refid]:
            print(f"disagg request {rid} diverged from the colocated "
                  f"run: {drouter.results[rid]} != {dref_out[refid]}")
            ok = False

    # the relay must drain: every spill was either restored on a decode
    # replica or discarded on the failover path — an entry left behind
    # is a leak
    if len(relay):
        print(f"relay pool not drained: {len(relay)} entries, "
              f"{relay.bytes_used} bytes")
        ok = False
    if not drouter.drain_all(timeout=60):
        print("disagg drain never completed")
        ok = False
    for name, rep in drouter.replicas.items():
        if name in (p_victim, d_victim):
            continue       # dead mid-flight: recovered only on revive
        acct = rep.raw.block_accounting()
        if not (acct["free"] + acct["cached"] == acct["total"]
                and acct["backed"] == 0 and acct["squeezed"] == 0):
            print(f"disagg replica {name} drained ledger not clean: "
                  f"{acct}")
            ok = False
    if drouter.live_streams():
        print(f"disagg streams survived the drain: "
              f"{drouter.live_streams()}")
        ok = False
    if d_violations:
        print(f"disagg per-replica ledger violations: "
              f"{d_violations[:3]}")
        ok = False
    print(f"disagg post-drain states: {drouter.states()}")
    drouter.stop()

    if not ok:
        print(_repro(args, "router"))
    print("ROUTER_CHAOS: OK" if ok else "ROUTER_CHAOS: FAIL")
    return 0 if ok else 1


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--serving", action="store_true",
                      help="run the serving-engine chaos suite instead "
                           "of the train-loop parity run")
    mode.add_argument("--http", action="store_true",
                      help="run the network-layer chaos suite against a "
                           "live HTTP/SSE front door")
    mode.add_argument("--router", action="store_true",
                      help="run the kill-a-replica chaos suite against a "
                           "ReplicaRouter over N in-process replicas")
    mode.add_argument("--train", action="store_true",
                      help="run the train-loop chaos parity suite "
                           "(the default; the flag names it explicitly)")
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--rate", type=float, default=0.2,
                    help="per-step fault probability for the random schedule")
    ap.add_argument("--requests", type=int, default=14,
                    help="--serving/--http/--router: requests offered "
                         "over the run")
    ap.add_argument("--replicas", type=int, default=3,
                    help="--router: engine replicas behind the router")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--no-corrupt-newest", action="store_true",
                    help="skip the corrupt-newest-checkpoint tier")
    args = ap.parse_args()

    if args.serving:
        return serving_main(args)
    if args.http:
        return http_main(args)
    if args.router:
        return router_main(args)

    import jax
    import jax.numpy as jnp

    import paddle_tpu.observability as obs
    from paddle_tpu.models import llama
    from paddle_tpu.observability import numerics
    from paddle_tpu.distributed.resilience import (FaultInjector,
                                                   ResilientTrainLoop,
                                                   ResumableIterator,
                                                   SimulatedCrash,
                                                   atomic_ckpt)

    # numerics on for BOTH runs (stat probes never change the math, so
    # parity still holds bit-exactly) — the nan_inject below must leave
    # a provenance trail naming its layer
    obs.enable()
    numerics.enable()
    cfg = llama.tiny_llama(vocab=64, hidden=32, layers=2, heads=4,
                           kv_heads=2, seq=16, ffn=64)
    steps = args.steps
    rng = np.random.RandomState(args.seed)
    batches = [jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 16)),
                           dtype=jnp.int32) for _ in range(steps + 4)]
    eval_batch = batches[-1]

    step_jit = jax.jit(lambda s, t: llama.train_step(s, t, cfg, lr=1e-3))
    eval_jit = jax.jit(lambda p, t: llama.loss_fn(p, t, cfg))

    def init_state():
        return llama.init_train_state(cfg, jax.random.PRNGKey(args.seed))

    def data_iter():
        return ResumableIterator(lambda e: iter(batches))

    # -- clean reference ---------------------------------------------------
    clean = ResilientTrainLoop(step_jit, init_state(), data_iter())
    s_clean = clean.run(steps)
    clean_pos = clean.data.state_dict()
    clean_loss = float(eval_jit(s_clean.params, eval_batch))
    print(f"clean run: {steps} steps, eval loss {clean_loss:.6f}")

    # -- chaos run ---------------------------------------------------------
    # seeded random schedule, with the canonical menu guaranteed present:
    # a NaN gradient in the first half and a crash in the second
    inj = FaultInjector.random_schedule(
        seed=args.seed, n_steps=steps,
        kinds=("nan_grad", "storage_fail"), rate=args.rate)
    nan_layer = 1
    menu = [("nan_grad", max(1, steps // 3)),
            (f"nan_inject:{nan_layer}", max(2, steps // 2)),
            ("crash", 2 * steps // 3)]
    inj = FaultInjector(inj.pending + menu)
    print(f"fault schedule: {inj.pending}")

    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_run_")
    ckpt_dir = os.path.join(workdir, "ckpt")
    crashes = 0
    corrupted = args.no_corrupt_newest
    while True:
        loop = ResilientTrainLoop(step_jit, init_state(), data_iter(),
                                  ckpt_dir=ckpt_dir, ckpt_every=2,
                                  injector=inj)
        try:
            s_chaos = loop.run(steps)
            break
        except SimulatedCrash as e:
            crashes += 1
            print(f"worker died ({e}); relaunching (auto-resume)")
            if not corrupted:
                ckpts = atomic_ckpt.list_checkpoints(ckpt_dir)
                if ckpts:
                    victim = os.path.join(ckpts[-1][1], "a00000.bin")
                    with open(victim, "r+b") as f:
                        f.write(b"bitrot!!")
                    print(f"corrupted newest checkpoint "
                          f"(step {ckpts[-1][0]}) to exercise fallback")
                    corrupted = True
        if crashes > 8:
            print(_repro(args, "train"))
            print("CHAOS_PARITY: FAIL (crash loop)")
            return 1

    chaos_loss = float(eval_jit(s_chaos.params, eval_batch))
    chaos_pos = loop.data.state_dict()
    events = [e["kind"] for e in loop.events]
    print(f"chaos run: {crashes} crashes, {loop.total_retries} retries, "
          f"{loop.skipped_batches} skipped, final events {events}")
    print(f"chaos eval loss {chaos_loss:.6f}")

    ok = True
    # NaN provenance end-to-end: the nan_inject rollback must have named
    # the injected layer, in the rollback event AND the post-mortem
    want = f"llama.layer:{nan_layer}"
    pm_path = os.path.join(workdir, "postmortem.json")
    obs.flight_recorder.dump(pm_path)
    import json
    with open(pm_path) as f:
        pm = json.load(f)
    got = (pm.get("numerics") or {}).get("provenance")
    print(f"nan_inject provenance: post-mortem names {got!r} "
          f"(injected {want!r})")
    if got != want:
        print(f"PROVENANCE: FAIL (expected {want!r})")
        ok = False
    named = [e for e in pm.get("events", [])
             if e.get("kind") == "rollback" and e.get("first_bad") == want]
    if not named:
        print("PROVENANCE: FAIL (no rollback flight event carries "
              f"first_bad={want!r})")
        ok = False
    for a, b in zip(jax.tree_util.tree_leaves(s_clean.params),
                    jax.tree_util.tree_leaves(s_chaos.params)):
        if not np.allclose(np.asarray(a), np.asarray(b),
                           rtol=1e-6, atol=1e-6):
            diff = float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
            print(f"param mismatch: max abs diff {diff}")
            ok = False
    if chaos_pos != clean_pos:
        print(f"dataloader position mismatch: {chaos_pos} != {clean_pos}")
        ok = False
    if abs(chaos_loss - clean_loss) > 1e-6:
        print(f"final-loss mismatch: {chaos_loss} != {clean_loss}")
        ok = False
    if loop.skipped_batches != 0:
        print(f"unexpected skipped batches: {loop.skipped_batches}")
        ok = False

    if not ok:
        print(_repro(args, "train"))
    print("CHAOS_PARITY: OK" if ok else "CHAOS_PARITY: FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
