#!/usr/bin/env python
"""Chaos run: a tiny llama pretrain loop under a seeded random fault
schedule, asserting final-state parity with a clean run.

The CI-grade end-to-end for distributed/resilience: the driver plays the
role of the elastic launcher — every SimulatedCrash kills the "process"
(the ResilientTrainLoop) and a fresh loop auto-resumes from the newest
valid checkpoint; after the first crash the newest checkpoint is
deliberately corrupted to exercise the fallback tier. A run passes when
the faulted job reaches the SAME final parameters (allclose), the same
final eval loss, and the same dataloader position as an uninterrupted
run of equal total steps.

    JAX_PLATFORMS=cpu python tools/chaos_run.py --steps 12 --seed 7

Wired into the suite as tests/test_resilience.py::test_chaos_run_llama_parity
(slow lane: PADDLE_TPU_FULL_TESTS=1).
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--rate", type=float, default=0.2,
                    help="per-step fault probability for the random schedule")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--no-corrupt-newest", action="store_true",
                    help="skip the corrupt-newest-checkpoint tier")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import llama
    from paddle_tpu.distributed.resilience import (FaultInjector,
                                                   ResilientTrainLoop,
                                                   ResumableIterator,
                                                   SimulatedCrash,
                                                   atomic_ckpt)

    cfg = llama.tiny_llama(vocab=64, hidden=32, layers=2, heads=4,
                           kv_heads=2, seq=16, ffn=64)
    steps = args.steps
    rng = np.random.RandomState(args.seed)
    batches = [jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 16)),
                           dtype=jnp.int32) for _ in range(steps + 4)]
    eval_batch = batches[-1]

    step_jit = jax.jit(lambda s, t: llama.train_step(s, t, cfg, lr=1e-3))
    eval_jit = jax.jit(lambda p, t: llama.loss_fn(p, t, cfg))

    def init_state():
        return llama.init_train_state(cfg, jax.random.PRNGKey(args.seed))

    def data_iter():
        return ResumableIterator(lambda e: iter(batches))

    # -- clean reference ---------------------------------------------------
    clean = ResilientTrainLoop(step_jit, init_state(), data_iter())
    s_clean = clean.run(steps)
    clean_pos = clean.data.state_dict()
    clean_loss = float(eval_jit(s_clean.params, eval_batch))
    print(f"clean run: {steps} steps, eval loss {clean_loss:.6f}")

    # -- chaos run ---------------------------------------------------------
    # seeded random schedule, with the canonical menu guaranteed present:
    # a NaN gradient in the first half and a crash in the second
    inj = FaultInjector.random_schedule(
        seed=args.seed, n_steps=steps,
        kinds=("nan_grad", "storage_fail"), rate=args.rate)
    menu = [("nan_grad", max(1, steps // 3)), ("crash", 2 * steps // 3)]
    inj = FaultInjector(inj.pending + menu)
    print(f"fault schedule: {inj.pending}")

    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_run_")
    ckpt_dir = os.path.join(workdir, "ckpt")
    crashes = 0
    corrupted = args.no_corrupt_newest
    while True:
        loop = ResilientTrainLoop(step_jit, init_state(), data_iter(),
                                  ckpt_dir=ckpt_dir, ckpt_every=2,
                                  injector=inj)
        try:
            s_chaos = loop.run(steps)
            break
        except SimulatedCrash as e:
            crashes += 1
            print(f"worker died ({e}); relaunching (auto-resume)")
            if not corrupted:
                ckpts = atomic_ckpt.list_checkpoints(ckpt_dir)
                if ckpts:
                    victim = os.path.join(ckpts[-1][1], "a00000.bin")
                    with open(victim, "r+b") as f:
                        f.write(b"bitrot!!")
                    print(f"corrupted newest checkpoint "
                          f"(step {ckpts[-1][0]}) to exercise fallback")
                    corrupted = True
        if crashes > 8:
            print("CHAOS_PARITY: FAIL (crash loop)")
            return 1

    chaos_loss = float(eval_jit(s_chaos.params, eval_batch))
    chaos_pos = loop.data.state_dict()
    events = [e["kind"] for e in loop.events]
    print(f"chaos run: {crashes} crashes, {loop.total_retries} retries, "
          f"{loop.skipped_batches} skipped, final events {events}")
    print(f"chaos eval loss {chaos_loss:.6f}")

    ok = True
    for a, b in zip(jax.tree_util.tree_leaves(s_clean.params),
                    jax.tree_util.tree_leaves(s_chaos.params)):
        if not np.allclose(np.asarray(a), np.asarray(b),
                           rtol=1e-6, atol=1e-6):
            diff = float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
            print(f"param mismatch: max abs diff {diff}")
            ok = False
    if chaos_pos != clean_pos:
        print(f"dataloader position mismatch: {chaos_pos} != {clean_pos}")
        ok = False
    if abs(chaos_loss - clean_loss) > 1e-6:
        print(f"final-loss mismatch: {chaos_loss} != {clean_loss}")
        ok = False
    if loop.skipped_batches != 0:
        print(f"unexpected skipped batches: {loop.skipped_batches}")
        ok = False

    print("CHAOS_PARITY: OK" if ok else "CHAOS_PARITY: FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
