"""Perf sweep on the local chip: MoE bench-config train-step variants.

Locates the dense_base vs gmm dispatch gap at the bench shape (r5: the
dense path measured 0.927x vs the gmm path's 0.997x) and sweeps the knobs
around it: dispatch form, remat policy, batch. Prints tokens/s + MFU per
variant. Not part of the test suite.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def bench_cfg(**kw):
    from paddle_tpu.models import moe
    return moe.MoEConfig(
        vocab_size=32768, hidden_size=2048, intermediate_size=6144,
        moe_intermediate_size=1408, num_layers=12, num_heads=16,
        num_kv_heads=8, head_dim=128, num_experts=16, top_k=2,
        n_shared_experts=2, first_dense_layers=1, max_seq_len=2048,
        remat=True, **kw)


def run(name, cfg, batch=8, seq=2048):
    from bench import _peak_flops, _time_train, _release
    from paddle_tpu.models import moe
    opt = {"optimizer": "adafactor", "param_dtype": jnp.bfloat16}
    try:
        tps = _time_train(moe, cfg, batch, seq, opt, n_steps=10)
        mfu = moe.flops_per_token(cfg, seq) * tps / _peak_flops(
            jax.devices()[0])
        print(f"{name}: {tps:,.0f} tok/s  MFU={mfu:.3f} "
              f"vs_bar={mfu / 0.40:.4f}", flush=True)
    except Exception as e:
        print(f"{name}: FAILED {str(e)[:160]}", flush=True)
        _release()


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "dispatch"):
        run("gmm  b8 full", bench_cfg(dense_base=False))
        run("dense b8 full", bench_cfg(dense_base=True))
    if which in ("all", "remat"):
        run("gmm  b8 attn", bench_cfg(dense_base=False,
                                      remat_policy="attn"))
        run("dense b8 attn", bench_cfg(dense_base=True,
                                       remat_policy="attn"))
        run("gmm  b8 outs", bench_cfg(dense_base=False,
                                      remat_policy="outs"))
        run("dense b8 outs", bench_cfg(dense_base=True,
                                       remat_policy="outs"))
    if which in ("all", "batch"):
        run("gmm  b16 full", bench_cfg(dense_base=False), batch=16)
        run("dense b16 full", bench_cfg(dense_base=True), batch=16)


if __name__ == "__main__":
    main()
