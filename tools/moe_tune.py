#!/usr/bin/env python
"""moe tune: warm the grouped-matmul tiling cache; bisect MoE regressions.

The dropless-MoE hot path autotunes its Mosaic grouped-matmul tilings on
the *first encounter* of each shape (kernels/gmm_autotune.py) — a few
seconds of candidate timing folded into the first compile. This CLI runs
that warm-up ahead of time for a given MoEConfig, persists the winners
(``<cache>/gmm_tilings.json`` via paddle_tpu.jit.cache), and prints the
chosen-tilings table, so a production job's step 0 pays nothing::

    python tools/moe_tune.py --preset bench --batch 8 --seq 2048
    JAX_PLATFORMS=cpu python tools/moe_tune.py --preset tiny   # CPU smoke:
        # no Mosaic kernel to time, entries fall back to the heuristic
        # (printed as source=heuristic, kept in-process only)

    python tools/moe_tune.py --clear          # drop the persisted winners

``--bisect`` is the evidence-not-vibes regression harness (the r05
postmortem tool, docs/moe.md): it times the FULL train step with each
hot-path lever toggled independently — dispatch form (measured auto /
fused / gmm / dense), tiling autotune on/off, fused vs unfused routing,
remat-ladder rung — plus the per-phase breakdown of the base config
(bench.moe_phase_breakdown), and prints a delta table against the base::

    python tools/moe_tune.py --bisect --preset bench          # on the chip
    JAX_PLATFORMS=cpu python tools/moe_tune.py --bisect --preset tiny
    python tools/moe_tune.py --bisect --out /tmp/bisect.json  # JSON too

The expert-parallel overlap lever (FLAGS_moe_overlap_min_tokens) only
exists under an ep>1 mesh and is noted, not timed, on one chip.

The tier-1 lane runs both CPU smoke invocations
(tests/test_moe_dispatch.py) so the CLI can never rot.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _presets():
    import jax.numpy as jnp

    from paddle_tpu.models import moe

    return {
        # bench.py bench_moe — the round-metric config
        "bench": (moe.MoEConfig(
            vocab_size=32768, hidden_size=2048, intermediate_size=6144,
            moe_intermediate_size=1408, num_layers=12, num_heads=16,
            num_kv_heads=8, head_dim=128, num_experts=16, top_k=2,
            n_shared_experts=2, first_dense_layers=1, max_seq_len=2048,
            remat=True, dtype=jnp.bfloat16), 8, 2048),
        "16b": (moe.deepseek_moe_16b(), 4, 2048),
        "tiny": (moe.tiny_moe(), 2, 128),
    }


def gmm_shapes(cfg, batch: int, seq: int, ep: int = 1, dp: int = 1):
    """Every ``grouped_matmul`` call-site shape of the dropless pipeline
    for one step: per MoE layer, A = batch*seq*top_k expert-sorted rows
    hit the fused gate|up GEMM ([m,h] @ [E,h,2f]) and the down GEMM
    ([m,f] @ [E,f,h]). Single program: m = A, all E experts,
    full_rows=True. Expert parallelism (psum AND a2a forms): each rank's
    GEMM runs over its E//ep-expert shard with m = A/dp rows — or
    m = A/(2*dp) per double-buffered half, the default when the
    shared-expert overlap is on — with zero-padded tails
    (full_rows=False). Returns deduplicated (m, k, n, E_groups,
    full_rows) matching the autotune cache keys exactly."""
    T = batch * seq
    A = T * cfg.top_k
    h, f, E = cfg.hidden_size, cfg.moe_intermediate_size, cfg.num_experts
    variants = [(A, E, True)]
    if ep > 1:
        variants += [(A // dp, E // ep, False),
                     (A // (2 * dp), E // ep, False)]
    shapes = []
    for m, groups, full in variants:
        shapes += [(m, h, 2 * f, groups, full), (m, f, h, groups, full)]
    return sorted(set(shapes))


def _bisect_levers():
    """(name, config overrides, flag overrides) — each toggles ONE lever
    of the hot path off the base config."""
    return [
        ("dispatch=fused", {"dispatch": "fused"}, {}),
        ("dispatch=gmm", {"dispatch": "gmm"}, {}),
        ("dispatch=dense", {"dispatch": "dense"}, {}),
        ("autotune-off (heuristic tilings)", {"dispatch": "gmm"},
         {"moe_gmm_autotune": False}),
        ("unfused-routing", {"fused_router": False}, {}),
        ("remat=outs", {"remat_policy": "outs"}, {}),
        ("remat=attn", {"remat_policy": "attn"}, {}),
    ]


def run_bisect(cfg, batch, seq, out_path=None, levers="all"):
    """Time the full train step per lever; print the delta table."""
    import dataclasses
    import json

    import jax
    import jax.numpy as jnp

    from bench import _peak_flops, _release, _time_train, \
        moe_phase_breakdown
    from paddle_tpu.framework.flags import get_flags, set_flags
    from paddle_tpu.models import moe

    opt = {"optimizer": "adafactor", "param_dtype": jnp.bfloat16}
    dev = jax.devices()[0]

    def tps_of(c, flag_over):
        saved = get_flags(list(flag_over)) if flag_over else {}
        try:
            if flag_over:
                set_flags(flag_over)
            return _time_train(moe, c, batch, seq, opt, n_steps=3)
        finally:
            if flag_over:
                set_flags(saved)
            _release()

    wanted = None if levers in (None, "all") else {
        s.strip() for s in levers.split(",")}
    rows = []
    base_tps = tps_of(cfg, {})
    rows.append(("base (dispatch=auto)", base_tps, 0.0))
    for name, cfg_over, flag_over in _bisect_levers():
        if wanted is not None and not any(w in name for w in wanted):
            continue
        if cfg.remat is False and name.startswith("remat="):
            continue                 # lever does not exist on this config
        c = dataclasses.replace(cfg, **cfg_over)
        try:
            tps = tps_of(c, flag_over)
            rows.append((name, tps, (tps - base_tps) / base_tps * 100.0))
        except Exception as e:
            print(f"{name}: FAILED {str(e)[:160]}", flush=True)

    print(f"\nbisect @ batch={batch} seq={seq} "
          f"E={cfg.num_experts} top_k={cfg.top_k} "
          f"backend={jax.default_backend()}")
    w = max(len(r[0]) for r in rows)
    for name, tps, delta in rows:
        mfu = moe.flops_per_token(cfg, seq) * tps / _peak_flops(dev)
        print(f"  {name.ljust(w)}  {tps:>10,.0f} tok/s  "
              f"mfu={mfu:.3f}  {delta:+6.2f}% vs base")
    print("  (moe_overlap_min_tokens lever: ep>1 meshes only — "
          "not timed on one chip)")

    phases = moe_phase_breakdown(cfg, batch, seq)
    print(f"\nper-phase breakdown (one MoE layer, fwd+bwd, "
          f"layer_ms={phases['layer_ms']}):")
    for p, ms in phases["phase_ms"].items():
        print(f"  {p:<11} {ms:>9.3f} ms")

    if out_path:
        doc = {"batch": batch, "seq": seq,
               "levers": [{"name": n, "tokens_per_sec": round(t, 1),
                           "delta_pct": round(d, 2)}
                          for n, t, d in rows]}
        doc.update(phases)
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
        print(f"\nwrote {out_path}")
    return 0


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--preset", choices=("bench", "16b", "tiny"),
                    default="bench")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--bisect", action="store_true",
                    help="time the train step per hot-path lever and "
                         "print the delta table + phase breakdown")
    ap.add_argument("--out", default=None,
                    help="with --bisect: also write the table as JSON")
    ap.add_argument("--levers", default="all",
                    help="with --bisect: comma-separated substring "
                         "filter of lever names (the CI smoke runs one)")
    ap.add_argument("--ep", type=int, default=1,
                    help="also warm the per-rank shapes of an ep-way mesh")
    ap.add_argument("--dp", type=int, default=1,
                    help="token-shard count (dp*sp) of that mesh — the "
                         "per-rank row count is A/dp")
    ap.add_argument("--dtype", choices=("bfloat16", "float32"),
                    default="bfloat16")
    ap.add_argument("--cache-dir", default=None,
                    help="override the persist location "
                         "(FLAGS_jit_cache_dir)")
    ap.add_argument("--clear", action="store_true",
                    help="drop the persisted tiling winners and exit")
    args = ap.parse_args()

    if args.cache_dir:
        from paddle_tpu.framework.flags import set_flags

        set_flags({"jit_cache_dir": args.cache_dir})

    from paddle_tpu.jit import cache as jcache
    from paddle_tpu.kernels import gmm_autotune

    if args.clear:
        gmm_autotune.clear(persisted=True)
        print(f"cleared {jcache.cache_path(gmm_autotune.PERSIST_NAME)}")
        return 0

    import jax
    import jax.numpy as jnp

    cfg, batch, seq = _presets()[args.preset]
    batch = args.batch or batch
    seq = args.seq or seq
    if args.bisect:
        return run_bisect(cfg, batch, seq, out_path=args.out,
                          levers=args.levers)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    backend = jax.default_backend()
    print(f"backend={backend}  preset={args.preset}  batch={batch} "
          f"seq={seq} experts={cfg.num_experts} top_k={cfg.top_k}\n"
          f"persist: {jcache.cache_path(gmm_autotune.PERSIST_NAME)} "
          f"(measured winners only)\n")

    rows = []
    for m, k, n, E, full in gmm_shapes(cfg, batch, seq, ep=args.ep,
                                       dp=args.dp):
        tri = gmm_autotune.get_tilings(m, k, n, E, dtype, full)
        if tri is None:
            rows.append(((m, k, n, E, full), "ragged_dot", "-", "-", "-"))
            continue
        # re-read the entry so the table shows measured vs heuristic
        src = "heuristic"
        for key, source, _t in gmm_autotune.entries():
            if f"m={m}|k={k}|n={n}|E={E}|" in key and \
                    f"full_rows={full}|" in key:
                src = source
        rows.append(((m, k, n, E, full), src) + tuple(map(str, tri)))

    hdr = ("(m, k, n, E, full_rows)", "source", "fwd", "dgrad", "wgrad")
    widths = [max(len(str(r[i])) for r in rows + [hdr]) for i in range(5)]
    for r in [hdr] + rows:
        print("  ".join(str(v).ljust(w) for v, w in zip(r, widths)))
    n_meas = sum(1 for r in rows if r[1] == "measured")
    print(f"\n{len(rows)} shapes; {n_meas} measured"
          + ("" if backend == "tpu" else
             " (no TPU backend: heuristic fallback, nothing persisted)"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
