#!/usr/bin/env python
"""moe tune: pre-populate the grouped-matmul tiling cache offline.

The dropless-MoE hot path autotunes its Mosaic grouped-matmul tilings on
the *first encounter* of each shape (kernels/gmm_autotune.py) — a few
seconds of candidate timing folded into the first compile. This CLI runs
that warm-up ahead of time for a given MoEConfig, persists the winners
(``<cache>/gmm_tilings.json`` via paddle_tpu.jit.cache), and prints the
chosen-tilings table, so a production job's step 0 pays nothing::

    python tools/moe_tune.py --preset bench --batch 8 --seq 2048
    JAX_PLATFORMS=cpu python tools/moe_tune.py --preset tiny   # CPU smoke:
        # no Mosaic kernel to time, entries fall back to the heuristic
        # (printed as source=heuristic, kept in-process only)

    python tools/moe_tune.py --clear          # drop the persisted winners

The tier-1 lane runs the CPU smoke invocation (tests/test_moe_dispatch.py)
so the CLI can never rot.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _presets():
    import jax.numpy as jnp

    from paddle_tpu.models import moe

    return {
        # bench.py bench_moe — the round-metric config
        "bench": (moe.MoEConfig(
            vocab_size=32768, hidden_size=2048, intermediate_size=6144,
            moe_intermediate_size=1408, num_layers=12, num_heads=16,
            num_kv_heads=8, head_dim=128, num_experts=16, top_k=2,
            n_shared_experts=2, first_dense_layers=1, max_seq_len=2048,
            remat=True, dtype=jnp.bfloat16), 8, 2048),
        "16b": (moe.deepseek_moe_16b(), 4, 2048),
        "tiny": (moe.tiny_moe(), 2, 128),
    }


def gmm_shapes(cfg, batch: int, seq: int, ep: int = 1, dp: int = 1):
    """Every ``grouped_matmul`` call-site shape of the dropless pipeline
    for one step: per MoE layer, A = batch*seq*top_k expert-sorted rows
    hit the fused gate|up GEMM ([m,h] @ [E,h,2f]) and the down GEMM
    ([m,f] @ [E,f,h]). Single program: m = A, all E experts,
    full_rows=True. Expert parallelism (psum AND a2a forms): each rank's
    GEMM runs over its E//ep-expert shard with m = A/dp rows — or
    m = A/(2*dp) per double-buffered half, the default when the
    shared-expert overlap is on — with zero-padded tails
    (full_rows=False). Returns deduplicated (m, k, n, E_groups,
    full_rows) matching the autotune cache keys exactly."""
    T = batch * seq
    A = T * cfg.top_k
    h, f, E = cfg.hidden_size, cfg.moe_intermediate_size, cfg.num_experts
    variants = [(A, E, True)]
    if ep > 1:
        variants += [(A // dp, E // ep, False),
                     (A // (2 * dp), E // ep, False)]
    shapes = []
    for m, groups, full in variants:
        shapes += [(m, h, 2 * f, groups, full), (m, f, h, groups, full)]
    return sorted(set(shapes))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--preset", choices=("bench", "16b", "tiny"),
                    default="bench")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--ep", type=int, default=1,
                    help="also warm the per-rank shapes of an ep-way mesh")
    ap.add_argument("--dp", type=int, default=1,
                    help="token-shard count (dp*sp) of that mesh — the "
                         "per-rank row count is A/dp")
    ap.add_argument("--dtype", choices=("bfloat16", "float32"),
                    default="bfloat16")
    ap.add_argument("--cache-dir", default=None,
                    help="override the persist location "
                         "(FLAGS_jit_cache_dir)")
    ap.add_argument("--clear", action="store_true",
                    help="drop the persisted tiling winners and exit")
    args = ap.parse_args()

    if args.cache_dir:
        from paddle_tpu.framework.flags import set_flags

        set_flags({"jit_cache_dir": args.cache_dir})

    from paddle_tpu.jit import cache as jcache
    from paddle_tpu.kernels import gmm_autotune

    if args.clear:
        gmm_autotune.clear(persisted=True)
        print(f"cleared {jcache.cache_path(gmm_autotune.PERSIST_NAME)}")
        return 0

    import jax
    import jax.numpy as jnp

    cfg, batch, seq = _presets()[args.preset]
    batch = args.batch or batch
    seq = args.seq or seq
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    backend = jax.default_backend()
    print(f"backend={backend}  preset={args.preset}  batch={batch} "
          f"seq={seq} experts={cfg.num_experts} top_k={cfg.top_k}\n"
          f"persist: {jcache.cache_path(gmm_autotune.PERSIST_NAME)} "
          f"(measured winners only)\n")

    rows = []
    for m, k, n, E, full in gmm_shapes(cfg, batch, seq, ep=args.ep,
                                       dp=args.dp):
        tri = gmm_autotune.get_tilings(m, k, n, E, dtype, full)
        if tri is None:
            rows.append(((m, k, n, E, full), "ragged_dot", "-", "-", "-"))
            continue
        # re-read the entry so the table shows measured vs heuristic
        src = "heuristic"
        for key, source, _t in gmm_autotune.entries():
            if f"m={m}|k={k}|n={n}|E={E}|" in key and \
                    key.endswith(f"full_rows={full}"):
                src = source
        rows.append(((m, k, n, E, full), src) + tuple(map(str, tri)))

    hdr = ("(m, k, n, E, full_rows)", "source", "fwd", "dgrad", "wgrad")
    widths = [max(len(str(r[i])) for r in rows + [hdr]) for i in range(5)]
    for r in [hdr] + rows:
        print("  ".join(str(v).ljust(w) for v, w in zip(r, widths)))
    n_meas = sum(1 for r in rows if r[1] == "measured")
    print(f"\n{len(rows)} shapes; {n_meas} measured"
          + ("" if backend == "tpu" else
             " (no TPU backend: heuristic fallback, nothing persisted)"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
