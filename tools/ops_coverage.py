"""Coverage ledger generator: audits every op in the reference's
paddle/phi/ops/yaml/ops.yaml against this framework's public surface and
writes OPS_COVERAGE.md (the C9 ledger — SURVEY.md §2).

Categories:
  direct    — same name found on paddle_tpu / paddle_tpu.nn.functional /
              paddle_tpu.linalg / paddle_tpu.fft / paddle_tpu.sparse /
              paddle_tpu.geometric / Tensor method
  mapped    — known rename (e.g. elementwise_pow → pow, c_allreduce →
              distributed.all_reduce) or covered by a listed equivalent
  absorbed  — no user-facing surface in a jax/XLA design: fused/optimizer
              device kernels expressed through the generic dispatch +
              optimizer classes, AMP casts, memory ops XLA owns
  missing   — genuinely absent capability

Run:  python tools/ops_coverage.py            (writes OPS_COVERAGE.md)
"""
from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
YAML = "/root/reference/paddle/phi/ops/yaml/ops.yaml"

# renames / equivalent-surface mappings (reference name -> where we cover it)
MAPPED = {
    "elementwise_pow": "paddle.pow",
    "c_allgather": "distributed.all_gather",
    "c_allreduce_sum": "distributed.all_reduce",
    "c_broadcast": "distributed.broadcast",
    "c_concat": "distributed.all_gather + concat",
    "c_embedding": "fleet.layers.mpu VocabParallelEmbedding",
    "c_identity": "GSPMD (identity collective inserted by XLA)",
    "c_reduce_sum": "distributed.reduce",
    "c_reducescatter": "distributed.reduce_scatter",
    "c_scatter": "distributed.scatter",
    "c_sync_calc_stream": "device.synchronize (streams are XLA-ordered)",
    "c_sync_comm_stream": "device.synchronize",
    "all_reduce": "distributed.all_reduce",
    "all_gather": "distributed.all_gather",
    "all_to_all": "distributed.all_to_all",
    "reduce_scatter": "distributed.reduce_scatter",
    "p_recv": "distributed.recv",
    "p_send": "distributed.send",
    "send_v2": "distributed.send",
    "recv_v2": "distributed.recv",
    "barrier": "distributed.barrier",
    "bincount": "paddle.bincount",
    "broadcast_tensors": "paddle.broadcast_tensors",
    "dropout": "nn.functional.dropout",
    "embedding_grad_dense": "autodiff of F.embedding",
    "exponential_": "Tensor.exponential_ / distribution.Exponential",
    "full_batch_size_like": "paddle.full + shape arithmetic",
    "fused_softmax_mask": "XLA fusion of where+softmax",
    "fused_softmax_mask_upper_triangle": "causal mask fused by XLA",
    "gaussian": "paddle.randn / paddle.normal",
    "gaussian_inplace": "paddle.normal",
    "hardswish": "nn.functional.hardswish",
    "hsigmoid_loss": "F.adaptive_log_softmax_with_loss (hierarchical)",
    "increment": "paddle.increment",
    "less_than": "paddle.less_than",
    "matmul_with_flatten": "paddle.matmul + reshape (XLA fuses)",
    "matrix_rank_tol": "paddle.linalg.matrix_rank(tol=...)",
    "memcpy_d2h": "Tensor.cpu() (device_put)",
    "memcpy_h2d": "to_tensor/device_put",
    "mean_all": "paddle.mean",
    "remainder": "paddle.remainder",
    "repeat_interleave_with_tensor_index": "paddle.repeat_interleave",
    "reshard": "distributed.reshard",
    "softmax": "nn.functional.softmax",
    "strided_slice": "Tensor slicing (x[a:b:c])",
    "sync_batch_norm_": "nn.SyncBatchNorm (GSPMD batch stats psum)",
    "sync_batch_norm": "nn.SyncBatchNorm (GSPMD batch stats psum)",
    "tril_indices": "paddle.tril_indices",
    "triu_indices": "paddle.triu_indices",
    "truncated_gaussian_random": "nn.initializer.TruncatedNormal",
    "uniform_inplace": "Tensor.uniform_",
    "unpool": "nn.functional.max_unpool2d",
    "unpool3d": "nn.functional.max_unpool3d",
    "view_shape": "paddle.reshape / Tensor.view",
    "view_dtype": "Tensor.view(dtype) — bitcast",
    # interpolation family → F.interpolate(mode=...)
    "bicubic_interp": "F.interpolate(mode='bicubic')",
    "bilinear_interp": "F.interpolate(mode='bilinear')",
    "linear_interp": "F.interpolate(mode='linear')",
    "nearest_interp": "F.interpolate(mode='nearest')",
    "trilinear_interp": "F.interpolate(mode='trilinear')",
    # metrics / losses
    "accuracy": "metric.Accuracy",
    "auc": "metric.Auc",
    "bce_loss": "F.binary_cross_entropy",
    "sigmoid_cross_entropy_with_logits":
        "F.binary_cross_entropy_with_logits",
    "cross_entropy_with_softmax": "F.softmax_with_cross_entropy",
    "kldiv_loss": "F.kl_div",
    "hinge_loss": "F.hinge_embedding_loss",
    "identity_loss": "paddle.mean/sum (reduction modes)",
    "warpctc": "F.ctc_loss",
    # attention family → Pallas flash kernel + SDPA surface
    "flash_attn": "kernels/pallas_attention + F.scaled_dot_product_attention",
    "flash_attn_qkvpacked": "same kernel, packed layout unpacked at entry",
    "flash_attn_unpadded": "varlen via mask in SDPA",
    "flash_attn_varlen_qkvpacked": "varlen via mask in SDPA",
    "flashmask_attention": "SDPA with additive mask",
    "memory_efficient_attention": "kernels/pallas_attention (online softmax)",
    "sparse_attention": "sparse.nn.functional.attention",
    "calc_reduced_attn_scores": "flash kernel statistics (lse) internal",
    # fft
    "fft_c2c": "paddle.fft.fft/ifft",
    "fft_c2r": "paddle.fft.irfft",
    "fft_r2c": "paddle.fft.rfft",
    # rnn family
    "rnn": "nn.SimpleRNN/nn.RNN",
    "lstm": "nn.LSTM",
    "cudnn_lstm": "nn.LSTM (XLA scan lowering)",
    "gru": "nn.GRU",
    "gru_unit": "nn.GRUCell",
    "attention_lstm": "nn.LSTM + attention composition",
    # linalg / math
    "frobenius_norm": "paddle.linalg.norm(p='fro')",
    "inverse": "paddle.linalg.inv",
    "l1_norm": "paddle.norm(p=1)",
    "squared_l2_norm": "paddle.norm(p=2)**2",
    "matrix_rank_atol_rtol": "paddle.linalg.matrix_rank",
    "gammaincc": "paddle.igamma",
    "standard_gamma": "distribution.Gamma.sample / jax.random.gamma",
    "dirichlet": "distribution.Dirichlet.sample",
    # manipulation
    "fill": "paddle.full_like / Tensor.fill_",
    "reverse": "paddle.flip",
    "split_with_num": "paddle.split(num_or_sections=int)",
    "pad3d": "F.pad (n-d)",
    "pool2d": "F.avg_pool2d / F.max_pool2d",
    "pool3d": "F.avg_pool3d / F.max_pool3d",
    "max_pool3d_with_index": "F.max_pool3d + unpool3d indices",
    "im2sequence": "F.unfold (im2col)",
    "shuffle_channel": "F.channel_shuffle",
    "tanh_shrink": "F.tanhshrink",
    "depthwise_conv2d": "F.conv2d(groups=C)",
    "conv2d_transpose_bias": "F.conv2d_transpose(bias=...)",
    "spectral_norm": "nn.SpectralNorm",
    "segment_pool": "geometric.segment_sum/mean/max/min",
    "clip_by_norm": "nn.ClipGradByNorm",
    "check_numerics": "FLAGS check_nan_inf dispatch hook",
    "enable_check_model_nan_inf": "framework.flags.set_flags",
    "disable_check_model_nan_inf": "framework.flags.set_flags",
    "data": "static.data",
    "viterbi_decode": "text.viterbi_decode",
    "crf_decoding": "text.viterbi_decode",
    "graph_khop_sampler": "geometric.sample_neighbors (per hop)",
    "graph_sample_neighbors": "geometric.sample_neighbors",
    # quantization family
    "depthwise_conv2d_transpose": "F.conv2d_transpose(groups=C)",
    "fill_diagonal_tensor": "paddle.fill_diagonal (+ diagonal scatter)",
    "multiclass_nms3": "vision.ops.nms(scores, category_idxs)",
    "yolo_box_head": "vision.ops.yolo_box",
    "yolo_box_post": "vision.ops.yolo_box + vision.ops.nms",
    "box_clip": "paddle.clip on box tensors",
    "deformable_conv": "vision.ops.deform_conv2d (offset-sampled im2col "
                       "+ MXU matmul)",
}

# device/runtime kernels a jax/XLA design absorbs (no user surface in the
# reference python API either, or the surface is an optimizer/AMP class)
ABSORBED_PATTERNS = [
    (r"^(adadelta|adagrad|adam|adamax|adamw|lamb|momentum|rmsprop|sgd|"
     r"rprop|asgd|nadam|radam)_$",
     "optimizer classes apply the update rule in-graph "
     "(optimizer/, optimizer/functional.py)"),
    (r"^fused_", "XLA fusion / Pallas kernels (kernels/, incubate.nn)"),
    (r"^(check_finite_and_unscale_|update_loss_scaling_)$",
     "amp.GradScaler logic in-graph"),
    (r"^(coalesce_tensor|share_buffer|share_data)", "XLA buffer management"),
    (r"^(memcpy|save_combine|load_combine)", "io/framework.save+load"),
    (r"^(print|assert|pylayer|while|conditional_block|select_input|"
     r"select_output|array_|create_array)",
     "python control flow / lax.cond / lax.while_loop"),
    (r"^(distributed_lookup_table|distributed_push_sparse|pull_sparse|"
     r"push_gpups_sparse|pull_gpups_sparse|pull_box_sparse|"
     r"push_dense|pull_dense)",
     "parameter-server architecture (documented skip D19)"),
    (r"^(limit_by_capacity|prune_gate_by_capacity|random_routing|"
     r"global_gather|global_scatter|moe|number_count)",
     "models/moe.py GShard einsum dispatch"),
    (r"^(accuracy_check|get_tensor_from_selected_rows|"
     r"merge_selected_rows)", "no SelectedRows concept (dense jax arrays)"),
    (r"^(uniform_random_batch_size_like|seed)", "framework.random keys"),
    (r"^(dgc|dgc_momentum)", "deep gradient compression — legacy"),
    (r"^(partial_concat|partial_sum|row_conv|prelu)",
     "paddle.concat/sum slices; nn.functional.prelu"),
    (r"^c_", "XLA collectives over the mesh (distributed/collective.py)"),
    (r"^fake_(channel_wise_)?(quantize|dequantize)",
     "quantization/ fake-quant observers (QAT/PTQ, STE)"),
    (r"^(dequantize_abs_max|dequantize_log|quantize_linear|"
     r"apply_per_channel_scale|lookup_table_dequant)",
     "quantization/ observers"),
    (r"^(assign_out_|assign_value_|assign_pos|full_int_array|"
     r"full_with_tensor|shape64|set_value_with_tensor|view_slice|"
     r"trans_layout|npu_identity|depend|copy_to|set$|"
     r"index_select_strided|embedding_with_scaled_gradient)",
     "IR-internal/layout ops — jaxpr has no separate variants"),
    (r"^(merged_adam_|merged_momentum_|average_accumulates_|"
     r"decayed_adagrad|dpsgd|ftrl|sparse_momentum)",
     "multi-tensor/legacy optimizer kernels — one jit covers them "
     "(optimizer/functional.py)"),
    (r"^(sequence_conv|sequence_pool|match_matrix_tensor|pyramid_hash|"
     r"tdm_child|tdm_sampler|cvm|rank_attention|batch_fc|shuffle_batch|"
     r"add_position_encoding|affine_channel|bipartite_match|"
     r"collect_fpn_proposals|ctc_align|beam_search$|warprnnt)",
     "legacy LoD-tensor / PS-era ops (no LoD concept; documented skip)"),
    (r"^(decode_jpeg|read_file)",
     "host-side image IO (PIL/np in io pipeline; device path is arrays)"),
    (r"^chunk_eval$",
     "legacy NER-chunk eval kernel with no python surface in the "
     "reference (fluid-era; metric.* covers the metric zoo)"),
    (r"^(mp_allreduce_sum|partial_allgather|sync_calc_stream)",
     "XLA collectives / stream ordering"),
    (r"^(disable|enable)_check_model",
     "framework.flags"),
]

SURFACES = []


def _surfaces():
    sys.path.insert(0, REPO)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    import paddle_tpu.nn as nn
    from paddle_tpu.core.tensor import Tensor

    mods = [("paddle", paddle), ("F", F), ("nn", nn),
            ("linalg", paddle.linalg), ("fft", paddle.fft),
            ("sparse", paddle.sparse),
            ("geometric", paddle.geometric),
            ("signal", paddle.signal),
            ("distributed", paddle.distributed),
            ("incubate.nn.functional",
             paddle.incubate.nn.functional),
            ("vision.ops", paddle.vision.ops),
            ("nn.quant", paddle.nn.quant)]
    return mods, Tensor


def classify(name, mods, Tensor):
    base = name.rstrip("_")
    for label, mod in mods:
        for cand in (name, base):
            if hasattr(mod, cand):
                return "direct", f"{label}.{cand}"
    for cand in (name, base):
        if hasattr(Tensor, cand):
            return "direct", f"Tensor.{cand}"
    if name in MAPPED:
        return "mapped", MAPPED[name]
    for pat, why in ABSORBED_PATTERNS:
        if re.match(pat, name):
            return "absorbed", why
    return "missing", ""


BWD_YAML = "/root/reference/paddle/phi/ops/yaml/backward.yaml"
SPARSE_YAML = "/root/reference/paddle/phi/ops/yaml/sparse_ops.yaml"
FUSED_YAML = "/root/reference/paddle/phi/ops/yaml/fused_ops.yaml"
STRINGS_YAML = "/root/reference/paddle/phi/ops/yaml/strings_ops.yaml"

# sparse_ops.yaml kernels -> where the capability lives here
SPARSE_MAPPED = {
    "batch_norm_": "sparse.nn.BatchNorm",
    "sync_batch_norm_": "sparse.nn.SyncBatchNorm",
    "conv3d_implicit_gemm": "sparse.nn.functional.subm_conv3d_igemm",
    "divide_scalar": "sparse.divide (scalar rhs broadcasts)",
    "scale": "internal of sparse.neg/rad2deg/deg2rad (ref unary.py:698 "
             "uses it the same way; no public python surface)",
    "acos": "kernel-only in the reference (no python sparse.acos); "
            "values-map composes via jnp",
    "acosh": "kernel-only in the reference; values-map composes via jnp",
    "to_dense": "SparseCooTensor.to_dense / SparseCsrTensor.to_dense",
    "to_sparse_coo": "Tensor.to_sparse_coo / sparse.sparse_coo_tensor",
    "to_sparse_csr": "SparseCooTensor.to_sparse_csr",
    "values": "SparseCooTensor.values attr",
    "indices": "SparseCooTensor.indices attr",
    "full_like": "dense full_like + sparse.mask_as",
    "fused_attention": "sparse.nn.functional.attention",
    "maxpool": "sparse.nn.functional.max_pool3d",
}


def audit_extra_yamls(mods, Tensor):
    """Audit sparse/fused/strings op sets. Returns (title, rows) pairs."""
    paddle = dict(mods)["paddle"]  # bootstrapped once by _surfaces()

    out = []
    names = re.findall(r"^- op\s*:\s*(\S+)", open(SPARSE_YAML).read(), re.M)
    rows = []
    for name in sorted(set(names)):
        base = name.rstrip("_")
        if hasattr(paddle.sparse, base):
            rows.append((name, "direct", f"sparse.{base}"))
        elif hasattr(paddle.sparse.nn.functional, base):
            rows.append((name, "direct", f"sparse.nn.functional.{base}"))
        elif name in SPARSE_MAPPED:
            rows.append((name, "mapped", SPARSE_MAPPED[name]))
        else:
            rows.append((name, "missing", ""))
    out.append(("sparse_ops.yaml", rows))

    # device-fusion patterns whose capability is the unfused surface + XLA
    # fusion (or a Pallas kernel). These families deliberately span the
    # whole current yaml — fused kernels ARE the absorbed-by-design case on
    # TPU — so this audit documents the design rather than hunts gaps; the
    # direct check above still upgrades an op once a real surface exists,
    # and an op outside these families would surface as "missing".
    fusion_pats = [
        r"_xpu$", r"^fused_", r"^fusion_", r"^fc$", r"^gemm_epilogue$",
        r"^(multihead_matmul|self_dp_attention|qkv_unpack_mha|"
        r"skip_layernorm|add_group_norm_silu|squeeze_excitation_block|"
        r"resnet_basic_block|resnet_unit|max_pool2d_v2|"
        r"fp8_fp8_half_gemm_fused|distributed_fused_lamb_init|"
        r"blha_get_max_len|variable_length_memory_efficient_attention)$",
    ]
    names = re.findall(r"^- op\s*:\s*(\S+)", open(FUSED_YAML).read(), re.M)
    rows = []
    for name in sorted(set(names)):
        base = name.rstrip("_")
        if hasattr(paddle.incubate.nn.functional, base):
            rows.append((name, "direct", f"incubate.nn.functional.{base}"))
            continue
        cat, where = classify(name, mods, Tensor)
        if cat == "missing" and any(re.search(p, name)
                                    for p in fusion_pats):
            cat, where = "absorbed", (
                "fused device kernel — XLA fusion of the unfused "
                "surface / Pallas kernels (kernels/)")
        rows.append((name, cat, where))
    out.append(("fused_ops.yaml", rows))

    names = re.findall(r"^- op\s*:\s*(\S+)", open(STRINGS_YAML).read(), re.M)
    rows = [(n, "absorbed",
             "StringTensor has no TPU story by design — host-side python "
             "strings + tokenizers (PARITY C2)") for n in sorted(set(names))]
    out.append(("strings_ops.yaml", rows))
    return out


def audit_backward(mods, Tensor):
    """Audit backward.yaml: every grad op maps to autodiff (jax.grad/vjp) of
    its forward op, so backward coverage == forward coverage of the base op.
    Higher-order entries (_double_grad/_triple_grad) are covered the same way
    — jax composes grad-of-grad (tests/test_autograd.py higher-order tests).
    Returns rows (grad_op, order, forward_category)."""
    names = re.findall(r"^- backward_op\s*:\s*(\S+)", open(BWD_YAML).read(),
                       re.M)
    rows = []
    for name in sorted(set(names)):
        base = re.sub(r"_(double_|triple_)?grad(_grad)?$", "", name)
        cat, where = classify(base, mods, Tensor)
        rows.append((name, base, cat, where))
    return rows


def main():
    ops = re.findall(r"^- op\s*:\s*(\S+)", open(YAML).read(), re.M)
    mods, Tensor = _surfaces()
    rows = [(name,) + classify(name, mods, Tensor) for name in sorted(ops)]
    counts = {}
    for _, cat, _ in rows:
        counts[cat] = counts.get(cat, 0) + 1
    total = len(rows)
    covered = total - counts.get("missing", 0)

    out = ["# OPS_COVERAGE — ledger vs paddle/phi/ops/yaml/ops.yaml",
           "",
           f"Generated by `python tools/ops_coverage.py` against the "
           f"reference's {total} forward ops.",
           "",
           f"| category | count |", "|---|---|"]
    for cat in ("direct", "mapped", "absorbed", "missing"):
        out.append(f"| {cat} | {counts.get(cat, 0)} |")
    out.append(f"| **covered** | **{covered}/{total} "
               f"({100.0 * covered / total:.1f}%)** |")
    brows = audit_backward(mods, Tensor)
    bcounts = {}
    for _, _, cat, _ in brows:
        bcounts[cat] = bcounts.get(cat, 0) + 1
    btotal = len(brows)
    bcovered = btotal - bcounts.get("missing", 0)
    out += [
        "", "## Backward ops (backward.yaml)", "",
        f"All {btotal} grad ops are jax autodiff of the forward surface — "
        "no per-op backward kernels exist in this design (the generic "
        "dispatch captures jax.vjp; higher-order = grad-of-grad, "
        "tests/test_autograd.py). A grad op is covered iff its forward "
        "op is:",
        "", "| forward category | grad ops |", "|---|---|"]
    for cat in ("direct", "mapped", "absorbed", "missing"):
        out.append(f"| {cat} | {bcounts.get(cat, 0)} |")
    out.append(f"| **covered** | **{bcovered}/{btotal} "
               f"({100.0 * bcovered / btotal:.1f}%)** |")
    miss_b = [r for r in brows if r[2] == "missing"]
    if miss_b:
        out += ["", "Missing-forward grad ops:",
                ""] + [f"- {n} (forward `{b}`)" for n, b, _, _ in miss_b]

    for title, xrows in audit_extra_yamls(mods, Tensor):
        xc = {}
        for _, cat, _ in xrows:
            xc[cat] = xc.get(cat, 0) + 1
        xt = len(xrows)
        xcov = xt - xc.get("missing", 0)
        out += ["", f"## {title}", "",
                f"{xcov}/{xt} covered "
                f"({', '.join(f'{k} {v}' for k, v in sorted(xc.items()))})",
                "", "| op | category | where |", "|---|---|---|"]
        for name, cat, where in xrows:
            out.append(f"| {name} | {cat} | {where} |")
        for name, cat, _ in xrows:
            if cat == "missing":
                print(f"  {title} missing: {name}")

    out += ["", "## ops.yaml detail", "",
            "| op | category | where |", "|---|---|---|"]
    for name, cat, where in rows:
        out.append(f"| {name} | {cat} | {where} |")
    with open(os.path.join(REPO, "OPS_COVERAGE.md"), "w") as f:
        f.write("\n".join(out) + "\n")
    print(f"covered {covered}/{total} ({100.0 * covered / total:.1f}%); "
          f"missing {counts.get('missing', 0)}")
    for name, cat, _ in rows:
        if cat == "missing":
            print("  missing:", name)


if __name__ == "__main__":
    sys.exit(main())
