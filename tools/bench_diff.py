#!/usr/bin/env python
"""Bench regression sentinel: diff two ``BENCH_r*.json`` rounds.

The r05 MoE regression (0.92x) sat unnoticed for two bench rounds
because nothing diffs consecutive ``BENCH_r*.json`` files — a human has
to remember last round's numbers. This tool is that diff:

    python tools/bench_diff.py                  # two latest rounds in .
    python tools/bench_diff.py --dir /path      # ... in /path
    python tools/bench_diff.py BENCH_r04.json BENCH_r05.json

Per-metric relative delta against a configurable noise band (default
±3%); any regression beyond the band prints a human table and exits
nonzero — wire it after ``bench.py`` in CI and the next 0.92x pages
someone at the round it lands, not two rounds later.

Failed rounds are first-class: a round whose ``parsed`` block is empty
(the bench crashed, e.g. r04's RESOURCE_EXHAUSTED) cannot anchor a
diff, so the OLD side walks back to the newest earlier round that has
metrics (noted in the output). A NEW side without metrics is itself
reported as a regression — a bench that stopped producing numbers is
the worst kind of slowdown.

``--check ROUND.json`` is the CI arming of the sentinel: validate ONE
named round against the newest earlier usable round in its directory.
A round file that does not exist yet exits 0 ("pending") — so a tier-1
test can commit ``--check BENCH_r06.json`` today and the check arms
itself the moment that round lands; a landed round that regressed then
fails the suite at the round it happens, not two rounds later::

    python tools/bench_diff.py --check BENCH_r06.json

Exit codes: 0 ok (within band / pending / first round), 1 regression
(or unusable new round), 2 usage/IO error.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def round_number(path: str) -> Optional[int]:
    m = _ROUND_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def load_round(path: str) -> Dict:
    with open(path) as f:
        return json.load(f)


def metric_rows(doc: Dict) -> Dict[str, Dict]:
    """``{metric_name: row}`` of one round's usable rows. Rows that are
    failure markers (``*_failed`` placeholders, non-positive values)
    carry no comparable number and are skipped."""
    parsed = doc.get("parsed") or {}
    rows = parsed.get("metrics")
    if rows is None:
        rows = [parsed] if parsed.get("metric") else []
    out = {}
    for row in rows:
        name = row.get("metric")
        try:
            value = float(row.get("value"))
        except (TypeError, ValueError):
            continue
        if not name or name.endswith("_failed") or value <= 0:
            continue
        out[name] = row
    return out


def find_rounds(directory: str) -> List[str]:
    """BENCH_r*.json in ``directory``, round-ordered."""
    paths = [p for p in glob.glob(os.path.join(directory, "BENCH_r*.json"))
             if round_number(p) is not None]
    return sorted(paths, key=round_number)


def newest_earlier_usable(path: str) -> Tuple[Optional[str], Dict[str, Dict]]:
    """The newest round in ``path``'s directory with a LOWER round
    number and usable metrics — the shared walk-back behind the failed-
    round anchoring and ``--check``. Unreadable candidate rounds are
    skipped (one corrupt old file must not kill the sentinel)."""
    n = round_number(path)
    if n is None:
        return None, {}
    for prev in reversed(find_rounds(os.path.dirname(path) or ".")):
        pn = round_number(prev)
        if pn is None or pn >= n:
            continue
        try:
            rows = metric_rows(load_round(prev))
        except (OSError, json.JSONDecodeError):
            continue
        if rows:
            return prev, rows
    return None, {}


def resolve_old(old_path: str, notes: List[str]) -> Tuple[str, Dict[str, Dict]]:
    """The old anchor: ``old_path`` itself when it has metrics, else the
    newest EARLIER round in the same directory that does (a failed round
    cannot anchor a diff — exactly the r04 case)."""
    doc = load_round(old_path)
    rows = metric_rows(doc)
    if rows:
        return old_path, rows
    notes.append(
        f"note: {os.path.basename(old_path)} has no parsed metrics "
        f"(rc={doc.get('rc')}) — walking back to an earlier round")
    prev, rows = newest_earlier_usable(old_path)
    if prev is not None:
        notes.append(f"note: baseline round = {os.path.basename(prev)}")
        return prev, rows
    return old_path, {}


def diff_rows(old_rows: Dict[str, Dict], new_rows: Dict[str, Dict],
              band: float) -> List[Dict]:
    """One entry per metric in either round: relative delta + status
    (``ok`` / ``regressed`` / ``improved`` / ``added`` / ``removed``)."""
    out = []
    for name in sorted(set(old_rows) | set(new_rows)):
        o, n = old_rows.get(name), new_rows.get(name)
        if o is None:
            out.append({"metric": name, "old": None,
                        "new": float(n["value"]), "delta": None,
                        "status": "added"})
            continue
        if n is None:
            # a metric that stopped reporting is flagged, not failed:
            # rounds legitimately rename rows (r04 serving rows split
            # into bf16/int8 variants at r05)
            out.append({"metric": name, "old": float(o["value"]),
                        "new": None, "delta": None, "status": "removed"})
            continue
        ov, nv = float(o["value"]), float(n["value"])
        delta = nv / ov - 1.0
        status = "ok"
        if delta < -band:
            status = "regressed"
        elif delta > band:
            status = "improved"
        out.append({"metric": name, "old": ov, "new": nv,
                    "delta": delta, "status": status})
    return out


def render_table(entries: List[Dict], old_name: str, new_name: str,
                 band: float, out=sys.stdout) -> None:
    w = max([len(e["metric"]) for e in entries] + [len("metric")])
    out.write(f"bench diff: {old_name} -> {new_name} "
              f"(noise band ±{band:.1%})\n")
    out.write(f"{'metric':{w}}  {'old':>12}  {'new':>12}  "
              f"{'delta':>8}  status\n")
    out.write("-" * (w + 48) + "\n")
    for e in entries:
        old = f"{e['old']:.1f}" if e["old"] is not None else "-"
        new = f"{e['new']:.1f}" if e["new"] is not None else "-"
        delta = f"{e['delta']:+.1%}" if e["delta"] is not None else "-"
        mark = " <-- REGRESSION" if e["status"] == "regressed" else ""
        out.write(f"{e['metric']:{w}}  {old:>12}  {new:>12}  "
                  f"{delta:>8}  {e['status']}{mark}\n")


def check_round(path: str, band: float) -> int:
    """``--check``: validate one round against its newest earlier usable
    round. Missing file = pending (0); no earlier usable round = first
    round (0); regression beyond the band = 1."""
    name = os.path.basename(path)
    if round_number(path) is None:
        # a misnamed target would stay 'pending' forever — a sentinel
        # that can never arm is a config error, not a pass
        print(f"bench_diff: --check target {name!r} does not match "
              "BENCH_r<N>.json", file=sys.stderr)
        return 2
    if not os.path.exists(path):
        print(f"check: {name} not produced yet — pending (the check "
              "arms itself when the round lands)")
        return 0
    try:
        new_doc = load_round(path)
    except (OSError, json.JSONDecodeError) as e:
        print(f"bench_diff: {e}", file=sys.stderr)
        return 2
    new_rows = metric_rows(new_doc)
    if not new_rows:
        print(f"REGRESSION: {name} has no parsed metrics "
              f"(rc={new_doc.get('rc')}) — the bench itself failed")
        return 1
    old_path, old_rows = newest_earlier_usable(path)
    if not old_rows:
        print(f"check: {name} is the first usable round under "
              f"{os.path.dirname(path) or '.'!r} — nothing to diff")
        return 0
    entries = diff_rows(old_rows, new_rows, band)
    render_table(entries, os.path.basename(old_path), name, band)
    regressed = [e for e in entries if e["status"] == "regressed"]
    if regressed:
        names = ", ".join(e["metric"] for e in regressed)
        print(f"\nREGRESSION: {len(regressed)} metric(s) beyond the "
              f"-{band:.1%} band: {names}")
        return 1
    print("\nok: no regression beyond the noise band")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diff two bench rounds; nonzero exit on regression")
    ap.add_argument("old", nargs="?", default=None,
                    help="old round JSON (default: second-latest in --dir)")
    ap.add_argument("new", nargs="?", default=None,
                    help="new round JSON (default: latest in --dir)")
    ap.add_argument("--dir", default=".",
                    help="directory scanned for BENCH_r*.json (auto mode)")
    ap.add_argument("--band", type=float, default=3.0,
                    help="noise band in percent (default 3.0): deltas "
                         "inside ±band%% are ok")
    ap.add_argument("--check", default=None, metavar="ROUND.json",
                    help="validate ONE round against the newest earlier "
                         "usable round in its directory; a round not "
                         "produced yet is 'pending' (exit 0) — the "
                         "tier-1 sentinel mode")
    args = ap.parse_args(argv)
    band = args.band / 100.0

    if args.check is not None:
        if args.old is not None or args.new is not None:
            ap.error("--check takes no positional rounds")
        return check_round(args.check, band)

    if (args.old is None) != (args.new is None):
        ap.error("pass both OLD and NEW, or neither (auto mode)")
    if args.old is None:
        rounds = find_rounds(args.dir)
        if len(rounds) < 2:
            print(f"bench_diff: need >= 2 BENCH_r*.json under "
                  f"{args.dir!r}, found {len(rounds)}", file=sys.stderr)
            return 2
        args.old, args.new = rounds[-2], rounds[-1]

    notes: List[str] = []
    try:
        old_path, old_rows = resolve_old(args.old, notes)
        new_doc = load_round(args.new)
    except (OSError, json.JSONDecodeError) as e:
        print(f"bench_diff: {e}", file=sys.stderr)
        return 2
    new_rows = metric_rows(new_doc)

    for note in notes:
        print(note)
    if not new_rows:
        print(f"REGRESSION: {os.path.basename(args.new)} has no parsed "
              f"metrics (rc={new_doc.get('rc')}) — the bench itself "
              "failed")
        return 1
    if not old_rows:
        print(f"bench_diff: no usable baseline round for "
              f"{os.path.basename(args.old)}", file=sys.stderr)
        return 2

    entries = diff_rows(old_rows, new_rows, band)
    render_table(entries, os.path.basename(old_path),
                 os.path.basename(args.new), band)
    regressed = [e for e in entries if e["status"] == "regressed"]
    if regressed:
        names = ", ".join(e["metric"] for e in regressed)
        print(f"\nREGRESSION: {len(regressed)} metric(s) beyond the "
              f"-{band:.1%} band: {names}")
        return 1
    print("\nok: no regression beyond the noise band")
    return 0


if __name__ == "__main__":
    sys.exit(main())
