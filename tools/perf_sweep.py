"""Perf sweep on the local chip: 2.6B llama train-step variants.

Tries cross-entropy chunking x batch size and prints tokens/s + MFU for
each so we can pick the best bench configuration. Edit the loop literals
in main() to sweep other axes (remat policy, optimizer mode). Not part of
the test suite.
"""
import gc
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def run(name, cfg, batch, seq, optimizer, param_dtype):
    from bench import _peak_flops
    from paddle_tpu.models import llama
    try:
        state = llama.init_train_state(
            cfg, jax.random.PRNGKey(0), optimizer=optimizer,
            param_dtype=param_dtype)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size)
        step = jax.jit(
            lambda s, t: llama.train_step(s, t, cfg, optimizer=optimizer),
            donate_argnums=0)
        for _ in range(2):
            state, loss = step(state, tokens)
        import numpy as np
        float(np.asarray(loss))
        n = 5
        t0 = time.perf_counter()
        for _ in range(n):
            state, loss = step(state, tokens)
        float(np.asarray(loss))
        dt = time.perf_counter() - t0
        tps = batch * seq * n / dt
        mfu = (llama.flops_per_token(cfg, seq) * tps
               / _peak_flops(jax.devices()[0]))
        print(f"{name}: {tps:,.0f} tok/s  MFU={mfu:.3f}", flush=True)
    except Exception as e:
        print(f"{name}: FAILED {str(e)[:160]}", flush=True)
    finally:
        state = tokens = step = loss = None
        gc.collect()
        jax.clear_caches()


def main():
    from paddle_tpu.models import llama
    base = dict(vocab_size=32768, hidden_size=3072, intermediate_size=8192,
                num_layers=24, num_heads=24, num_kv_heads=8, head_dim=128,
                max_seq_len=2048)
    for chunks in (1, 8):
        for batch in (8, 16):
            cfg = llama.LlamaConfig(remat=True, loss_chunks=chunks, **base)
            run(f"2.6b ce_chunks={chunks} b={batch}", cfg, batch, 2048,
                "adafactor", jnp.bfloat16)
    return 0


if __name__ == "__main__":
    sys.exit(main())
