#!/usr/bin/env python
"""Serve: launch the HTTP/SSE front door over an LLMEngine.

The production entrypoint shape over paddle_tpu.serving.http: build a
model (``--model tiny`` initializes random weights at the configured
size — the hermetic default; point ``--params`` at a saved pytree for
real weights), wire the engine exactly as the bench/serving docs
describe (``--decode-kernel/--spec-tokens/--prefix-cache/--kv-int8``
pass straight through), and serve until SIGTERM/Ctrl-C — both of which
DRAIN: admission stops (503 + Connection: close), in-flight streams
finish up to FLAGS_serve_drain_s, then the process exits 0.

    JAX_PLATFORMS=cpu python tools/serve.py --port 8000 --max-new 32
    curl -N -XPOST localhost:8000/v1/generate \\
         -d '{"prompt": [1,2,3], "max_new_tokens": 8}'
    curl localhost:8000/readyz

Engine/obs flags ride ``--flags name=value,...`` (paddle set_flags
names, e.g. ``--flags serve_drain_s=5,obs_enabled=true``). ``--port 0``
binds an ephemeral port; the bound address is printed as
``serving on http://HOST:PORT`` (the subprocess smoke test parses it).
"""
import argparse
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_engine(args):
    import dataclasses

    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import llama
    from paddle_tpu.serving import (AdmissionConfig, LLMEngine,
                                    ResilientEngine)

    if args.model != "tiny":
        raise SystemExit(f"unknown --model {args.model!r} (have: tiny)")
    cfg = dataclasses.replace(
        llama.tiny_llama(vocab=args.vocab, hidden=args.hidden,
                         layers=args.layers, heads=args.heads,
                         kv_heads=args.kv_heads, seq=args.max_len,
                         ffn=args.hidden * 2),
        dtype=jnp.dtype(args.dtype).type)
    params = llama.init_params(cfg, jax.random.PRNGKey(args.seed))
    if args.params:
        raise SystemExit("--params loading is not wired yet; "
                         "--model tiny serves random weights")
    if args.int8:
        params = jax.jit(llama.quantize_params)(params)
    draft_params = draft_cfg = None
    if args.spec_tokens > 0 and args.draft_layers > 0:
        draft_cfg = llama.draft_config(cfg, num_layers=args.draft_layers)
        draft_params = llama.init_params(draft_cfg,
                                         jax.random.PRNGKey(args.seed + 1))
    admission = AdmissionConfig(
        max_queue=args.max_queue,
        rate_tokens_per_s=args.rate_tokens_per_s,
        shed_free_frac=args.shed_free_frac)
    eng = LLMEngine(
        params, cfg, max_slots=args.max_slots,
        block_size=args.block_size, max_model_len=args.max_len,
        decode_steps=args.decode_steps,
        kv_dtype="int8" if args.kv_int8 else None,
        admission=admission,
        kv_swap_bytes=args.kv_swap_bytes,
        prefix_cache=args.prefix_cache,
        prefill_chunk=args.prefill_chunk,
        decode_kernel=args.decode_kernel,
        draft_params=draft_params, draft_config=draft_cfg,
        spec_tokens=max(1, args.spec_tokens), seed=args.seed)
    return ResilientEngine(eng)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="tiny",
                    help="model preset (tiny = random-weight tiny llama "
                         "at the --vocab/--hidden/... size)")
    ap.add_argument("--params", default=None,
                    help="reserved: path to saved weights")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000,
                    help="0 binds an ephemeral port (printed)")
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--kv-heads", type=int, default=2)
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-slots", type=int, default=4)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--max-len", type=int, default=256)
    ap.add_argument("--decode-steps", type=int, default=1)
    ap.add_argument("--decode-kernel", default="auto",
                    choices=("auto", "ragged", "bucketed"))
    ap.add_argument("--int8", action="store_true",
                    help="int8 weight-only params")
    ap.add_argument("--kv-int8", action="store_true",
                    help="int8 KV pools")
    ap.add_argument("--prefix-cache", action="store_true")
    ap.add_argument("--prefill-chunk", type=int, default=0)
    ap.add_argument("--spec-tokens", type=int, default=0,
                    help="speculative decoding: draft proposal depth "
                         "(0 = off; needs --draft-layers)")
    ap.add_argument("--draft-layers", type=int, default=0,
                    help="layers of the random-init draft model")
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--rate-tokens-per-s", type=float, default=0.0)
    ap.add_argument("--shed-free-frac", type=float, default=0.0)
    ap.add_argument("--kv-swap-bytes", type=int, default=0)
    ap.add_argument("--obs", action="store_true",
                    help="enable the observability registry + tracer")
    ap.add_argument("--obs-port", type=int, default=None,
                    help="also start the standalone observability HTTP "
                         "server on this port (0 = ephemeral, printed; "
                         "implies --obs). The front door itself serves "
                         "/metrics and /fleet/* too — this adds the "
                         "full obs surface: /trace.json, /requests.json,"
                         " /control/profile")
    ap.add_argument("--flags", default=None,
                    help="comma list of name=value paddle flags "
                         "(e.g. serve_drain_s=5)")
    args = ap.parse_args()

    import paddle_tpu.observability as obs
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.serving import HTTPFrontDoor

    if args.flags:
        staged = {}
        for item in filter(None, args.flags.split(",")):
            name, _, val = item.partition("=")
            staged[name.strip()] = val.strip()
        set_flags(staged)
    if args.obs or args.obs_port is not None:
        obs.enable()

    reng = build_engine(args)
    front = HTTPFrontDoor(reng, host=args.host, port=args.port)
    host, port = front.start()
    print(f"serving on http://{host}:{port}", flush=True)
    if args.obs_port is not None:
        srv = obs.start_http_server(port=args.obs_port)
        print(f"observability on http://{srv.host}:{srv.port}",
              flush=True)

    # SIGTERM (orchestrator) and SIGINT (Ctrl-C) both drain: stop
    # admission, finish in-flight streams up to FLAGS_serve_drain_s,
    # then exit cleanly. A second signal cuts the drain budget to 0.
    def on_signal(signum, _frame):
        if front.draining:
            front._drain_budget = 0.0
            return
        print(f"signal {signum}: draining "
              "(in-flight streams finish, new requests 503)", flush=True)
        front.begin_drain()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    while not front.wait_drained(timeout=0.2):
        pass
    front.stop()
    print("drained; bye", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
