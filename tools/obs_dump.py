#!/usr/bin/env python
"""obs dump: print a metrics table and write a Chrome trace.

Two modes (slow-lane tooling, like tools/chaos_run.py):

- attach to a snapshot file (written by ``observability.dump_snapshot``,
  the ``MetricsLogger`` hapi callback, or scraped from the exposition
  server's ``/snapshot.json``) and print the table::

      python tools/obs_dump.py --snapshot /tmp/obs/metrics.json

- run a tiny built-in workload with observability enabled, print the
  resulting table, and write ``snapshot.json`` + ``trace.json`` (open
  the latter in chrome://tracing or ui.perfetto.dev)::

      JAX_PLATFORMS=cpu python tools/obs_dump.py --demo serving --out /tmp/obs
      JAX_PLATFORMS=cpu python tools/obs_dump.py --demo train --out /tmp/obs
      JAX_PLATFORMS=cpu python tools/obs_dump.py --demo moe --out /tmp/obs
      JAX_PLATFORMS=cpu python tools/obs_dump.py --demo goodput --out /tmp/obs
      JAX_PLATFORMS=cpu python tools/obs_dump.py --demo numerics --out /tmp/obs

- pretty-print a crash flight-recorder dump (written on unhandled
  exception / watchdog timeout / SIGTERM when FLAGS_obs_postmortem_dir
  is set, or by ``observability.flight_recorder.dump``)::

      python tools/obs_dump.py --postmortem /tmp/obs/postmortem-1234-1.json

- print the per-request table (timelines + TTFT/TPOT exemplars) from a
  live exposition server's ``/requests.json`` — or a saved copy — worst
  request first; ``--watch`` refreshes it top-style::

      python tools/obs_dump.py --requests http://127.0.0.1:9464
      python tools/obs_dump.py --requests reqs.json --sort tpot
      python tools/obs_dump.py --requests http://127.0.0.1:9464 --watch

- print the live fleet dashboard (per-replica state, streams, queue,
  tokens, p95 TTFT/TPOT, cache hit rate, SLO burn) from a server's
  ``/fleet/replicas.json`` — obs server or serving front door both
  carry it; ``--watch`` refreshes it top-style::

      python tools/obs_dump.py --fleet http://127.0.0.1:9464 --watch

- print the windowed alert table (burn-rate + anomaly watchers) from a
  server's ``/alerts.json`` — obs server or serving front door both
  carry it; ``--watch`` refreshes it top-style::

      python tools/obs_dump.py --alerts http://127.0.0.1:9464 --watch
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fresh_ckpt_dir(workdir):
    """Checkpoint dir for a demo run, cleared first — a leftover
    checkpoint from a prior run with the same --out would auto-resume
    past the whole demo workload."""
    import shutil

    path = os.path.join(workdir, "ckpt")
    shutil.rmtree(path, ignore_errors=True)
    return path


def print_table(snap, out=sys.stdout):
    """Render a snapshot dict (exposition.snapshot format) as a table."""
    from paddle_tpu.observability.exposition import snapshot_rows

    rows = snapshot_rows(snap)
    if not rows:
        out.write("(no non-zero series)\n")
        return rows
    w0 = max(len(r[0]) for r in rows)
    w1 = max(len(r[1]) for r in rows)
    w2 = max(len(r[2]) for r in rows)
    out.write(f"{'metric':{w0}}  {'kind':{w1}}  {'labels':{w2}}  value\n")
    out.write("-" * (w0 + w1 + w2 + 12) + "\n")
    for name, kind, lbl, val in rows:
        out.write(f"{name:{w0}}  {kind:{w1}}  {lbl:{w2}}  {val}\n")
    return rows


def _fmt_ms(v):
    return f"{v:.1f}" if isinstance(v, (int, float)) else "-"


def print_request_table(payload, out=sys.stdout):
    """Render a ``/requests.json`` payload (requests_payload format):
    one row per request, worst first, plus the exemplar pointers that
    turn a p99 reading into a request_id."""
    rows = payload.get("requests") or []
    out.write(f"requests: {len(rows)} traced, "
              f"{payload.get('live', 0)} live "
              f"(sort={payload.get('sort', 'ttft')})\n")
    if not rows:
        out.write("(no traced requests — enable observability and "
                  "serve traffic)\n")
        return rows
    hdr = (f"{'request':>8} {'state':>6} {'tenant':>8} {'replica':>7} "
           f"{'queue_ms':>9} "
           f"{'ttft_ms':>9} {'tpot_ms':>8} {'tok/s':>8} {'tokens':>6} "
           f"{'cached':>6} {'offload':>7} {'preempt':>7} {'reason':>9}\n")
    out.write(hdr)
    out.write("-" * (len(hdr) - 1) + "\n")
    for r in rows:
        tps = r.get("decode_tps")
        tps_s = f"{tps:.1f}" if isinstance(tps, (int, float)) else "-"
        # terminal disposition (finished/shed/deadline_exceeded/
        # client_disconnected/drained); live rows and pre-r8 payloads
        # have none
        reason = r.get("reason") or "-"
        reason = {"deadline_exceeded": "deadline",
                  "client_disconnected": "gone"}.get(reason, reason)
        out.write(f"{str(r.get('request_id')):>8} "
                  f"{'live' if r.get('live') else 'done':>6} "
                  f"{str(r.get('tenant') or '-')[:8]:>8} "
                  # r16: which router replica hosted the stream
                  # (RequestTracer.annotate; "-" = single-engine)
                  f"{str(r.get('replica') or '-')[:7]:>7} "
                  f"{_fmt_ms(r.get('queue_ms')):>9} "
                  f"{_fmt_ms(r.get('ttft_ms')):>9} "
                  f"{_fmt_ms(r.get('tpot_ms')):>8} "
                  f"{tps_s:>8} "
                  f"{r.get('tokens', 0):>6} "
                  f"{r.get('cached_tokens', 0):>6} "
                  # r15: how the last swap-in restore met the offload
                  # tier ("hit" = prefetch-staged, "stall" = inline h2d;
                  # "-" = never swapped in)
                  f"{str(r.get('offload') or '-')[:7]:>7} "
                  f"{r.get('preemptions', 0):>7} "
                  f"{reason[:9]:>9}\n")
    for name, qs in (payload.get("exemplar_quantiles") or {}).items():
        for q, ex in qs.items():
            out.write(f"{q} {name} exemplar: request "
                      f"{ex.get('request_id')} "
                      f"({ex.get('value', 0) * 1e3:.1f} ms) — "
                      f"GET /request/{ex.get('request_id')}.json\n")
    audits = payload.get("audit") or []
    if audits:
        out.write(f"SLO audit entries: {len(audits)} (latest: request "
                  f"{audits[-1].get('request_id')} "
                  f"{'+'.join(audits[-1].get('reasons', []))})\n")
    return rows


def print_numerics_table(rows, out=sys.stdout):
    """Render the numerics stats table (observability.numerics.rows
    format): one row per (site, layer) with absmax/rms/NaN-count/
    overflow columns, plus the relative quant error for the paired
    pre/post-quant probe sites."""
    out.write(f"numerics: {len(rows)} stat row(s)\n")
    if not rows:
        out.write("(no landed stats — set FLAGS_obs_numerics and run an "
                  "instrumented workload)\n")
        return rows
    w = max([len(r["site"]) for r in rows] + [len("site")])
    hdr = (f"{'site':{w}} {'layer':>5} {'absmax':>10} {'rms':>10} "
           f"{'nan/inf':>7} {'overflow':>8} {'quant_err':>9}\n")
    out.write(hdr)
    out.write("-" * (len(hdr) - 1) + "\n")
    for r in rows:
        layer = str(r["layer"]) if r["layer"] >= 0 else "-"
        qerr = (f"{r['quant_err']:.2e}" if r["quant_err"] is not None
                else "-")
        out.write(f"{r['site']:{w}} {layer:>5} {r['absmax']:>10.4g} "
                  f"{r['rms']:>10.4g} {r['nan_inf']:>7d} "
                  f"{r['overflow_frac']:>8.2%} {qerr:>9}\n")
    return rows


def _fetch_requests(src, sort):
    """The payload behind --requests: a URL (live server, ?sort= added)
    or a saved JSON file."""
    import json
    import urllib.parse
    import urllib.request

    if src.startswith(("http://", "https://")):
        # append /requests.json to the PATH (a caller-supplied query
        # string must survive, not have the path glued onto it)
        parts = urllib.parse.urlsplit(src)
        path = parts.path.rstrip("/")
        if not path.endswith("/requests.json"):
            path += "/requests.json"
        query = f"{parts.query}&sort={sort}" if parts.query \
            else f"sort={sort}"
        url = urllib.parse.urlunsplit(
            (parts.scheme, parts.netloc, path, query, ""))
        with urllib.request.urlopen(url, timeout=10) as r:
            return json.load(r)
    with open(src) as f:
        return json.load(f)


def requests_mode(src, sort, watch, interval):
    if not watch:
        print_request_table(_fetch_requests(src, sort))
        return 0
    import io as _io
    import time as _time

    try:
        while True:
            payload = _fetch_requests(src, sort)
            buf = _io.StringIO()
            print_request_table(payload, out=buf)
            # top-style refresh: clear + home, one atomic write
            sys.stdout.write("\x1b[2J\x1b[H" + buf.getvalue())
            sys.stdout.flush()
            _time.sleep(interval)
    except KeyboardInterrupt:
        return 0


_SPARK_GLYPHS = "▁▂▃▄▅▆▇█"


def _spark(values, width=12):
    """Render a value series as a unicode sparkline (r20): scaled to
    the series' own max, newest value last."""
    vals = [v for v in (values or [])[-width:]
            if isinstance(v, (int, float))]
    if not vals:
        return "-"
    hi = max(vals)
    if hi <= 0:
        return _SPARK_GLYPHS[0] * len(vals)
    return "".join(
        _SPARK_GLYPHS[min(len(_SPARK_GLYPHS) - 1,
                          int(v / hi * (len(_SPARK_GLYPHS) - 1)))]
        for v in vals)


def print_alert_table(doc, out=sys.stdout):
    """Render an ``/alerts.json`` payload: one row per (alert,
    instance) with its windowed signal value vs threshold, firing
    rows first."""
    rows = doc.get("alerts") or []
    firing = doc.get("firing")
    if firing is None:      # embedded post-mortem tails carry only rows
        firing = sorted({r.get("alert") for r in rows
                         if r.get("state") == "firing"})
    out.write(f"alerts: {len(rows)} row(s), "
              f"{len(firing)} firing{' (' + ', '.join(firing) + ')' if firing else ''} "
              f"[windows {doc.get('window_fast_s', '-')}s/"
              f"{doc.get('window_slow_s', '-')}s, "
              f"ring {doc.get('ring_size', '-')}/"
              f"{doc.get('samples', '-')} samples]\n")
    if not rows:
        out.write("(no alert specs evaluated — enable observability "
                  "and serve traffic)\n")
        return rows
    hdr = (f"{'alert':>24} {'instance':>9} {'state':>7} "
           f"{'value':>10} {'threshold':>10} {'window':>7}\n")
    out.write(hdr)
    out.write("-" * (len(hdr) - 1) + "\n")
    order = {"firing": 0, "ok": 1, "no_data": 2}
    for r in sorted(rows, key=lambda r: (order.get(r.get("state"), 3),
                                         r.get("alert", ""),
                                         r.get("instance", ""))):
        val = r.get("value")
        val_s = f"{val:.4g}" if isinstance(val, (int, float)) else "-"
        out.write(f"{str(r.get('alert'))[:24]:>24} "
                  f"{str(r.get('instance') or '-')[:9]:>9} "
                  f"{str(r.get('state')):>7} "
                  f"{val_s:>10} "
                  f"{r.get('threshold', 0):>10.4g} "
                  f"{r.get('window_s', 0):>6.0f}s\n")
    return rows


def _fetch_alerts(src):
    """The payload behind --alerts: a base URL (live obs server or
    serving front door; /alerts.json appended) or a saved JSON file."""
    import json
    import urllib.parse
    import urllib.request

    if src.startswith(("http://", "https://")):
        parts = urllib.parse.urlsplit(src)
        path = parts.path.rstrip("/")
        if not path.endswith("/alerts.json"):
            path += "/alerts.json"
        url = urllib.parse.urlunsplit(
            (parts.scheme, parts.netloc, path, parts.query, ""))
        with urllib.request.urlopen(url, timeout=10) as r:
            return json.load(r)
    with open(src) as f:
        return json.load(f)


def alerts_mode(src, watch, interval):
    if not watch:
        print_alert_table(_fetch_alerts(src))
        return 0
    import io as _io
    import time as _time

    try:
        while True:
            doc = _fetch_alerts(src)
            buf = _io.StringIO()
            print_alert_table(doc, out=buf)
            sys.stdout.write("\x1b[2J\x1b[H" + buf.getvalue())
            sys.stdout.flush()
            _time.sleep(interval)
    except KeyboardInterrupt:
        return 0


def print_fleet_table(doc, out=sys.stdout):
    """Render a ``/fleet/replicas.json`` payload: one row per replica
    (state, disagg role, streams, queue/slots, tokens, p95 latencies,
    cache hit rate, SLO burn) plus the fleet totals line."""
    rows = doc.get("replicas") or []
    totals = doc.get("totals") or {}
    out.write(f"fleet: {totals.get('replicas', len(rows))} replica(s), "
              f"{totals.get('healthy', '-')} healthy, "
              f"{totals.get('live_streams', '-')} live stream(s), "
              f"{totals.get('tokens', 0)} tokens"
              f"{'' if doc.get('router') else ' (no router attached)'}\n")
    if not rows:
        out.write("(no replicas in view — run a router with "
                  "observability enabled)\n")
        return rows
    hdr = (f"{'replica':>8} {'state':>9} {'role':>7} {'hb_age':>7} "
           f"{'streams':>7} {'queue':>5} {'slots':>5} {'tokens':>7} "
           f"{'ttft_p95':>9} {'tpot_p95':>9} {'cache':>6} {'burn':>6} "
           f"{'tok/s':>12}\n")
    out.write(hdr)
    out.write("-" * (len(hdr) - 1) + "\n")
    for r in rows:
        slo = r.get("slo") or {}
        burn = slo.get("burn_rate")
        cache = r.get("cache_hit_rate")
        cache_s = f"{cache:.0%}" if isinstance(cache, (int, float)) \
            else "-"
        burn_s = f"{burn:.2f}" if isinstance(burn, (int, float)) else "-"
        out.write(
            f"{str(r.get('replica')):>8} "
            f"{str(r.get('state') or '-'):>9} "
            f"{str(r.get('role') or '-'):>7} "
            f"{_fmt_ms(r.get('hb_age_s')):>7} "
            f"{r.get('streams', 0):>7} "
            f"{r.get('queue_depth', 0):>5} "
            f"{r.get('active_slots', 0):>5} "
            f"{r.get('tokens', 0):>7} "
            f"{_fmt_ms(r.get('ttft_p95_ms')):>9} "
            f"{_fmt_ms(r.get('tpot_p95_ms')):>9} "
            f"{cache_s:>6} {burn_s:>6} "
            f"{_spark(r.get('spark')):>12}\n")
    return rows


def _fetch_fleet(src):
    """The payload behind --fleet: a base URL (live obs server or
    serving front door; /fleet/replicas.json appended) or a saved JSON
    file."""
    import json
    import urllib.parse
    import urllib.request

    if src.startswith(("http://", "https://")):
        parts = urllib.parse.urlsplit(src)
        path = parts.path.rstrip("/")
        if not path.endswith("/fleet/replicas.json"):
            path += "/fleet/replicas.json"
        url = urllib.parse.urlunsplit(
            (parts.scheme, parts.netloc, path, parts.query, ""))
        with urllib.request.urlopen(url, timeout=10) as r:
            return json.load(r)
    with open(src) as f:
        return json.load(f)


def fleet_mode(src, watch, interval):
    if not watch:
        print_fleet_table(_fetch_fleet(src))
        return 0
    import io as _io
    import time as _time

    try:
        while True:
            doc = _fetch_fleet(src)
            buf = _io.StringIO()
            print_fleet_table(doc, out=buf)
            sys.stdout.write("\x1b[2J\x1b[H" + buf.getvalue())
            sys.stdout.flush()
            _time.sleep(interval)
    except KeyboardInterrupt:
        return 0


def demo_serving():
    """int8-everywhere serving demo under fire: int8 weight-only params
    AND int8 KV pools through the decode path (off-TPU this counts the
    bucketed fallback of the r12 ragged kernel in
    serving_decode_kernel_total{path} — the choice is never silent),
    with the r8 survivability layer engaged — a bounded admission queue
    sheds the over-offered request, one request expires at its deadline,
    and pool pressure preempts a slot whose KV swaps to the host tier
    and back — and the r10 prefix cache on: a follow-up request re-sends
    the first prompt and skips its cached prefix blocks entirely. The
    table shows the r6 decode metrics plus
    serving_{shed,deadline_exceeded,kv_swap_*}_total and the
    serving_prefix_cache_* family. A second, speculative engine (r13)
    then runs a synthetic high-agreement draft and prints the
    serving_spec_* line — multiple committed tokens per verify call."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu.observability as obs
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.models import llama
    from paddle_tpu.serving import AdmissionConfig, LLMEngine, ShedError

    # r20: sample the time-series ring on EVERY engine step (the demo
    # runs seconds, not minutes — the default 1s throttle would leave
    # the sparkline/alert tail empty)
    set_flags({"obs_ts_interval_s": 0.0})
    cfg = dataclasses.replace(
        llama.tiny_llama(vocab=64, hidden=32, layers=2, heads=4, kv_heads=2,
                         seq=128, ffn=64),
        dtype=jnp.float32)
    params = jax.jit(llama.quantize_params)(
        llama.init_params(cfg, jax.random.PRNGKey(0)))
    rng = np.random.default_rng(0)
    # num_blocks=5 with two 8-token prompts decoding 16 fresh tokens each:
    # the pool MUST preempt mid-run — with the host tier enabled the
    # victim swaps out and back instead of re-prefilling
    eng = LLMEngine(params, cfg, max_slots=2, block_size=8,
                    max_model_len=64, num_blocks=5, prompt_buckets=[8, 32],
                    kv_dtype="int8", kv_swap_bytes=1 << 20,
                    admission=AdmissionConfig(max_queue=3),
                    prefix_cache=True, prefix_cache_host_bytes=1 << 20)
    first_prompt = rng.integers(1, 64, size=12).tolist()
    eng.add_request(first_prompt, max_new_tokens=16)
    eng.add_request(rng.integers(1, 64, size=8).tolist(),
                    max_new_tokens=16)
    # third queued request: a deadline that has already passed — evicted
    # with finish reason deadline_exceeded on its trace
    eng.add_request(rng.integers(1, 64, size=4).tolist(),
                    max_new_tokens=4, deadline_s=0.0)
    # fourth: the bounded queue (max_queue=3) sheds it with a typed error
    try:
        eng.add_request(rng.integers(1, 64, size=4).tolist(),
                        max_new_tokens=4)
    except ShedError as e:
        print(f"load shed: {e}")
    results = eng.run()
    # re-send the first prompt: its full blocks stayed in the prefix
    # cache after the request finished, so this admission pins them and
    # prefills only the one-block suffix (a cache HIT)
    eng.add_request(first_prompt, max_new_tokens=4)
    results = eng.run()
    reg = obs.get_registry()
    print(f"demo serving: {len(results)} requests, "
          f"{sum(len(v) for v in results.values())} tokens "
          "(int8 weights + int8 KV pools)")
    print("decode prefix bucket: "
          f"{int(reg.gauge('serving_decode_prefix_bucket').labels().value)}"
          " tokens; decode recompiles: "
          f"{int(reg.counter('serving_decode_recompiles_total').labels().value)}"
          "; kv bytes/call: "
          f"{int(reg.gauge('serving_decode_kv_read_bytes').labels().value)}")

    def _c(name, **lbl):
        return int(reg.counter(name).labels(**lbl).value)

    # r12/r18: which decode path served the dispatches (the fused mega
    # megakernel and the ragged Pallas walk are TPU-only picks under
    # auto; this CPU demo counts their bucketed fallback — the choice is
    # never silent, so mega stays 0 here) and how many compiled decode
    # variants the cache holds
    print("decode kernel paths: "
          f"mega={_c('serving_decode_kernel_total', path='mega')} "
          f"ragged={_c('serving_decode_kernel_total', path='ragged')} "
          f"bucketed={_c('serving_decode_kernel_total', path='bucketed')} "
          f"dense={_c('serving_decode_kernel_total', path='dense')}; "
          "decode variants: "
          f"{int(reg.gauge('serving_decode_variants').labels().value)}")

    print("degraded modes: "
          f"shed={_c('serving_shed_total', reason='queue_full')} "
          f"deadline_exceeded={_c('serving_deadline_exceeded_total')} "
          f"kv_swap_out={_c('serving_kv_swap_out_total')} "
          f"kv_swap_in={_c('serving_kv_swap_in_total')}")
    # r15: the async offload tier behind the swap/spill traffic above —
    # prefetch hits consumed staged payloads, stalls paid h2d inline,
    # proactive spills moved cold cached blocks host-side in the
    # background (in-flight bytes are 0 at this drained point)
    print("kv offload: "
          f"prefetch_hits={_c('serving_kv_offload_prefetch_hits_total')} "
          f"stalls={_c('serving_kv_offload_stalls_total')} "
          "stall_seconds="
          f"{reg.counter('serving_kv_offload_stall_seconds_total').labels().value:.4f} "
          "proactive_spills="
          f"{_c('serving_kv_offload_proactive_spills_total')} "
          "inflight_bytes="
          f"{int(reg.gauge('serving_kv_offload_inflight_bytes').labels().value)}")
    print("prefix cache: "
          f"hits={_c('serving_prefix_cache_hits_total')} "
          f"misses={_c('serving_prefix_cache_misses_total')} "
          f"prefill_tokens_skipped="
          f"{_c('serving_prefill_tokens_skipped_total')} "
          "cached_blocks="
          f"{int(reg.gauge('serving_prefix_cache_blocks').labels().value)}")
    # r13: a speculative engine over the same model — the draft here is
    # the target itself (the synthetic high-agreement draft), so every
    # wave commits spec_tokens per slot off ONE batched verify call
    dense_params = llama.init_params(cfg, jax.random.PRNGKey(0))
    seng = LLMEngine(dense_params, cfg, max_slots=2, block_size=8,
                     max_model_len=64, prompt_buckets=[8, 32],
                     draft_params=dense_params, draft_config=cfg,
                     spec_tokens=4)
    for _ in range(2):
        seng.add_request(rng.integers(1, 64, size=6).tolist(),
                         max_new_tokens=12)
    seng.run()
    print("speculative: "
          f"proposed={_c('serving_spec_proposed_total')} "
          f"accepted={_c('serving_spec_accepted_total')} "
          "acceptance="
          f"{reg.gauge('serving_spec_acceptance_rate').labels().value:.2f} "
          "tokens/wave="
          f"{reg.gauge('serving_spec_tokens_per_wave').labels().value:.2f} "
          f"draft_steps={seng.spec_draft_steps} "
          f"verify_calls={seng.spec_verify_calls}")
    # r14: one real HTTP round-trip through the SSE front door — the
    # speculative engine serves one request over a socket, then the
    # serving_http_* family has non-zero evidence in the table
    import json as _json
    import urllib.request

    from paddle_tpu.serving import HTTPFrontDoor
    front = HTTPFrontDoor(seng)
    host, port = front.start()
    req = urllib.request.Request(
        f"http://{host}:{port}/v1/generate",
        data=_json.dumps({"prompt": rng.integers(1, 64, size=6).tolist(),
                          "max_new_tokens": 6,
                          "stream": False}).encode(),
        headers={"X-Tenant": "demo"}, method="POST")
    with urllib.request.urlopen(req, timeout=120) as resp:
        doc = _json.loads(resp.read())
    ready = urllib.request.urlopen(
        f"http://{host}:{port}/readyz", timeout=30).status
    front.stop()
    print(f"http front door: one round-trip -> {len(doc['tokens'])} "
          f"tokens ({doc['reason']}), readyz={ready}; "
          f"requests_total[200]={_c('serving_http_requests_total', code='200')} "
          f"client_disconnects={_c('serving_http_client_disconnects_total')} "
          "active_streams="
          f"{int(reg.gauge('serving_http_active_streams').labels().value)} "
          "send_queue_depth="
          f"{int(reg.gauge('serving_http_send_queue_depth').labels().value)}")
    print(f"finish reasons: {eng.finish_reasons}")

    # r17: two replicas behind a ReplicaRouter, then ONE fleet scrape —
    # every engine metric above lands replica-labeled from the router's
    # step threads, counters sum fleet-wide, gauges stay per-replica
    from paddle_tpu.observability import fleet as _fleet
    from paddle_tpu.serving import ReplicaRouter

    def _mk(**kw):
        return LLMEngine(llama.init_params(cfg, jax.random.PRNGKey(0)),
                         cfg, max_slots=2, block_size=8, max_model_len=64,
                         prompt_buckets=[8, 32], **kw)

    router = ReplicaRouter([_mk(), _mk()], idle_wait=0.001).start()
    shared = rng.integers(1, 64, size=16).tolist()
    rids = [router.submit(shared[:8] + shared[8:][:4 * i],
                          max_new_tokens=6) for i in range(4)]
    for rid in rids:
        router.wait(rid, timeout=120)
    router.check()
    fdoc = _fleet.replicas_payload()
    per = {r["replica"]: r.get("tokens", 0) for r in fdoc["replicas"]}
    fleet_tokens = _fleet.get_aggregator().fleet_counter_value(
        "serving_router_dispatch_total")
    print(f"fleet scrape: {fdoc['totals']['replicas']} replicas "
          f"({fdoc['totals'].get('healthy')} healthy), per-replica "
          f"tokens {per}, dispatches fleet-wide "
          f"{int(fleet_tokens)}")
    print_fleet_table(fdoc)
    router.stop()

    # r19: disaggregated prefill/decode — one prefill-role replica spills
    # finished prefills into the shared host relay, one decode-role
    # replica restores them with a batched h2d scatter and streams the
    # decode; the handoff line is the disagg evidence (outcomes counted,
    # relay drained back to 0 bytes)
    from paddle_tpu.serving.kv_swap import HostKVPool
    relay = HostKVPool(64 << 20, kind="relay")
    p_eng = _mk(role="prefill", relay=relay)
    d_eng = _mk(role="decode", relay=relay)
    drouter = ReplicaRouter([p_eng, d_eng], names=["p0", "d0"],
                            idle_wait=0.001).start()
    drids = [drouter.submit(rng.integers(1, 64, size=6).tolist(),
                            max_new_tokens=6) for _ in range(2)]
    for rid in drids:
        drouter.wait(rid, timeout=120)
    drouter.stop()
    # the handoff outcomes land replica-scoped (p0 spills, d0 restores)
    # — read them fleet-aggregated, like any dashboard would
    agg = _fleet.get_aggregator()
    print("disagg handoff: "
          "ok="
          f"{int(agg.fleet_counter_value('serving_disagg_handoffs_total', outcome='ok'))} "
          "restored="
          f"{int(agg.fleet_counter_value('serving_disagg_handoffs_total', outcome='restored'))} "
          f"bytes={p_eng.handoff_bytes} "
          "relay_bytes="
          f"{int(reg.gauge('serving_disagg_kv_relay_bytes').labels().value)} "
          f"handoff_resumes={drouter.handoff_resumes}")
    print()
    print_request_table(obs.requests_payload())

    # r20: the windowed alert table (burn-rate + anomaly watchers) over
    # everything the demo just did, plus the process-wide tok/s trend
    # from the time-series ring — the same rows /alerts.json serves
    from paddle_tpu.observability import timeseries as _tsmod

    print()
    print_alert_table(_tsmod.alerts_payload())
    rates = _tsmod.get_store().rate_series("serving_tokens_total", n=16)
    print(f"tok/s spark: {_spark(rates, width=16)} "
          f"(last {len(rates)} sample intervals)")


def demo_moe():
    """Two dropless-MoE programs over one routing shape: the second is a
    plan-cache hit — the table shows moe_plan_cache_{hits,misses}_total
    and moe_dispatch_fallbacks_total, the trace the per-layer
    moe.dispatch spans. (The moe_tiling_* counters need a TPU backend:
    grouped_matmul only consults the autotuner there.)"""
    import jax

    from paddle_tpu.kernels import moe_dispatch
    from paddle_tpu.models import moe

    moe_dispatch.clear_plan_cache()
    cfg = moe.tiny_moe()
    state = moe.init_train_state(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                cfg.vocab_size)
    # two programs over the same routing shape: the eval trace derives
    # the dispatch plan (miss), the grad trace reuses it (hit)
    jax.jit(lambda p: moe.loss_fn(p, tokens, cfg))(state.params)
    step = jax.jit(lambda p, t: jax.value_and_grad(
        lambda p: moe.loss_fn(p, t, cfg))(p))
    for _ in range(2):
        loss, _grads = step(state.params, tokens)
    print(f"demo moe: {cfg.num_layers} layers x {cfg.num_experts} experts, "
          f"loss {float(loss):.3f}")


def demo_train(workdir):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.distributed.resilience import ResilientTrainLoop

    def step_fn(state, batch):
        w = state["w"] - 0.1 * batch.mean()
        return {"w": w}, jnp.abs(w).sum()

    batches = [jnp.full((2,), 0.1 * (i + 1)) for i in range(8)]
    loop = ResilientTrainLoop(
        step_fn, {"w": jnp.ones((2,))}, batches,
        ckpt_dir=_fresh_ckpt_dir(workdir), ckpt_every=2,
        rng_key=None)
    loop.run(len(batches))
    print(f"demo train: {loop.step} steps, "
          f"{len([e for e in loop.events if e['kind']=='checkpoint_saved'])}"
          " checkpoints")


def demo_goodput(workdir):
    """Chaos-injected goodput demo: a resilient train run with an
    injected NaN (one rollback-retry) and periodic checkpoints, then the
    goodput report — bucket fractions summing to 1.0 — and a manual
    flight-recorder post-mortem dump."""
    import jax.numpy as jnp

    import paddle_tpu.observability as obs
    from paddle_tpu.distributed.resilience import (FaultInjector,
                                                   ResilientTrainLoop)

    def step_fn(state, batch):
        w = state["w"] - 0.1 * batch.mean()
        return {"w": w}, jnp.abs(w).sum()

    batches = [jnp.full((2,), 0.1 * (i + 1)) for i in range(8)]
    loop = ResilientTrainLoop(
        step_fn, {"w": jnp.ones((2,))}, batches,
        ckpt_dir=_fresh_ckpt_dir(workdir), ckpt_every=3,
        rng_key=None, injector=FaultInjector("nan_grad@4"))
    loop.run(len(batches))
    rep = obs.goodput.get_tracker().report()
    print(f"demo goodput: {loop.step} steps, "
          f"{loop.total_retries} rollback(s)")
    print(f"goodput ratio {rep['goodput_ratio']:.3f} over "
          f"{rep['total_seconds']:.3f}s:")
    for b, frac in rep["fractions"].items():
        if frac > 0:
            print(f"  {b:16s} {frac:7.2%}  "
                  f"({rep['seconds'][b]:.3f}s)")
    pm = obs.flight_recorder.dump(
        os.path.join(workdir, "postmortem.json"))
    print(f"post-mortem: {pm} "
          "(pretty-print with tools/obs_dump.py --postmortem)")


def demo_numerics(workdir):
    """Numerics-observatory demo: all three int8 sites report their
    quant-error budget (weight_only from llama.quantize_params,
    expert_int8 from moe.quantize_expert_params, kv_int8 from an int8-KV
    engine run), then a seeded ``nan_inject`` chaos step shows the
    per-layer stats ladder naming the poisoned layer in the rollback's
    provenance — the stats table prints it all."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu.observability as obs
    from paddle_tpu.distributed.resilience import (FaultInjector,
                                                   ResilientTrainLoop)
    from paddle_tpu.models import llama, moe
    from paddle_tpu.observability import numerics
    from paddle_tpu.serving import LLMEngine

    numerics.enable()
    cfg = dataclasses.replace(
        llama.tiny_llama(vocab=64, hidden=32, layers=2, heads=4, kv_heads=2,
                         seq=128, ffn=64),
        dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))

    # site 1: weight-only int8 (quantize pairs the pre/post tensors)
    qparams = jax.jit(llama.quantize_params)(params)
    # site 2: int8 expert weights
    moe.quantize_expert_params(
        moe.init_params(moe.tiny_moe(), jax.random.PRNGKey(1)))
    # site 3: int8 KV pools through a short int8-everywhere serving run
    rng = np.random.default_rng(0)
    eng = LLMEngine(qparams, cfg, max_slots=2, block_size=8,
                    max_model_len=64, prompt_buckets=[8, 32],
                    kv_dtype="int8")
    for _ in range(2):
        eng.add_request(rng.integers(1, 64, size=8).tolist(),
                        max_new_tokens=8)
    results = eng.run()

    # provenance: a seeded nan_inject poisons layer 1 for one attempt;
    # the ladder names it on the rollback, the retry recovers
    state = llama.init_train_state(cfg, jax.random.PRNGKey(2))
    batches = [jnp.asarray(rng.integers(1, 64, size=(2, 16)), jnp.int32)
               for _ in range(4)]
    step = jax.jit(lambda s, t: llama.train_step(s, t, cfg, lr=1e-3))
    loop = ResilientTrainLoop(step, state, batches,
                              injector=FaultInjector("nan_inject:1@1"))
    loop.run(len(batches))
    rollbacks = [e for e in loop.events if e["kind"] == "rollback"]
    numerics.flush()
    print(f"demo numerics: {len(results)} requests served int8-KV, "
          f"{loop.step} train steps, {len(rollbacks)} rollback(s)")
    first_bad = rollbacks[0].get("first_bad") if rollbacks else None
    print(f"nan_inject provenance: first bad layer = {first_bad}")
    reg = obs.get_registry()
    for site in ("weight_only", "expert_int8", "kv_int8"):
        v = reg.gauge("numerics_quant_error").labels(site=site).value
        print(f"quant-error budget {site}: {v:.2e}")
    print()
    print_numerics_table(numerics.rows())
    pm = obs.flight_recorder.dump(os.path.join(workdir, "postmortem.json"))
    print(f"\npost-mortem (numerics section embedded): {pm}")


def print_postmortem(path, out=sys.stdout):
    """Pretty-print one flight-recorder post-mortem JSON."""
    import json
    import time as _time

    with open(path) as f:
        doc = json.load(f)
    when = _time.strftime("%Y-%m-%d %H:%M:%S",
                          _time.localtime(doc.get("unix_time", 0)))
    out.write(f"post-mortem  trigger={doc.get('trigger')}  "
              f"pid={doc.get('pid')}  {when}\n")
    err = doc.get("error")
    if err:
        out.write(f"error: {err.get('type')}: {err.get('message')}\n")
    gp = doc.get("goodput")
    if gp:
        out.write(f"goodput ratio {gp.get('goodput_ratio', 0):.3f} "
                  f"over {gp.get('total_seconds', 0):.3f}s (")
        out.write(", ".join(
            f"{b} {f:.1%}" for b, f in gp.get("fractions", {}).items()
            if f > 0.0005) + ")\n")
    spans = doc.get("open_spans") or {}
    if any(spans.values()):
        out.write("open spans at dump:\n")
        for tid, names in spans.items():
            out.write(f"  thread {tid}: {' > '.join(names)}\n")
    events = doc.get("events") or []
    out.write(f"\nlast {len(events)} events:\n")
    t_end = events[-1]["t"] if events else 0.0
    for ev in events:
        rest = {k: v for k, v in ev.items() if k not in ("t", "kind")}
        detail = "  ".join(f"{k}={v}" for k, v in rest.items())
        out.write(f"  {ev['t'] - t_end:+9.3f}s  {ev['kind']:20s} "
                  f"{detail}\n")
    reqs = doc.get("requests")
    if reqs:
        out.write("\nrequests at dump:\n")
        print_request_table(reqs, out=out)
    num = doc.get("numerics")
    if num:
        out.write("\nnumerics at dump:\n")
        if num.get("provenance"):
            out.write(f"NaN provenance: first bad layer = "
                      f"{num['provenance']}\n")
        print_numerics_table(num.get("rows") or [], out=out)
    ts = doc.get("timeseries")
    if ts:
        out.write("\ntimeseries tail at dump (the trajectory into the "
                  "failure):\n")
        entries = ts.get("entries") or []
        # one sparkline per watched signal over the embedded tail,
        # newest value printed beside it
        signals = {}
        for e in entries:
            for k, v in (e.get("signals") or {}).items():
                signals.setdefault(k, []).append(
                    v if isinstance(v, (int, float)) else None)
        t_end = entries[-1]["t"] if entries else 0.0
        if entries:
            out.write(f"  {len(entries)} entries spanning "
                      f"{t_end - entries[0]['t']:.1f}s\n")
        for k in sorted(signals):
            vals = [v for v in signals[k] if v is not None]
            last = f"{vals[-1]:.4g}" if vals else "-"
            out.write(f"  {k:32s} {_spark(signals[k], width=24):>24} "
                      f"last={last}\n")
        fired = [e for e in entries if e.get("firing")]
        for e in fired[-5:]:
            out.write(f"  {e['t'] - t_end:+9.3f}s firing: "
                      f"{', '.join(e['firing'])}\n")
        if ts.get("alerts"):
            out.write("final alert table:\n")
            print_alert_table({"alerts": ts["alerts"]}, out=out)
    metrics = doc.get("metrics")
    if metrics:
        out.write("\nmetrics at dump:\n")
        print_table(metrics, out=out)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--snapshot", default=None,
                    help="print the table from an existing JSON snapshot")
    ap.add_argument("--postmortem", default=None,
                    help="pretty-print a flight-recorder post-mortem dump")
    ap.add_argument("--requests", default=None, metavar="URL_OR_FILE",
                    help="print the per-request table from a live "
                         "exposition server base URL (/requests.json is "
                         "appended) or a saved payload file")
    ap.add_argument("--sort", default="ttft",
                    choices=("ttft", "tpot", "queue", "tokens",
                             "finished"),
                    help="--requests sort column (worst/highest first)")
    ap.add_argument("--fleet", default=None, metavar="URL_OR_FILE",
                    help="print the per-replica fleet table from a live "
                         "server base URL (/fleet/replicas.json is "
                         "appended; obs server or serving front door) "
                         "or a saved payload file")
    ap.add_argument("--alerts", default=None, metavar="URL_OR_FILE",
                    help="print the windowed alert table from a live "
                         "server base URL (/alerts.json is appended; "
                         "obs server or serving front door) or a saved "
                         "payload file")
    ap.add_argument("--watch", action="store_true",
                    help="with --requests/--fleet/--alerts URL: refresh "
                         "the table top-style until interrupted")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="--watch refresh period in seconds")
    ap.add_argument("--flags", default=None, metavar="PREFIX",
                    nargs="?", const="obs_",
                    help="print registered FLAGS_* (value/default/help); "
                         "optional prefix filter, default obs_")
    ap.add_argument("--demo", choices=("serving", "train", "moe",
                                       "goodput", "numerics"),
                    default=None,
                    help="run a tiny built-in workload with obs enabled")
    ap.add_argument("--out", default="./obs_dump",
                    help="demo mode: directory for snapshot.json/trace.json")
    args = ap.parse_args()

    if args.snapshot:
        from paddle_tpu.observability import load_snapshot

        print_table(load_snapshot(args.snapshot))
        return 0
    if args.postmortem:
        print_postmortem(args.postmortem)
        return 0
    if args.requests:
        return requests_mode(args.requests, args.sort, args.watch,
                             args.interval)
    if args.fleet:
        return fleet_mode(args.fleet, args.watch, args.interval)
    if args.alerts:
        return alerts_mode(args.alerts, args.watch, args.interval)
    if args.flags is not None:
        import paddle_tpu.observability  # noqa: F401  (registers FLAGS_obs_*)
        from paddle_tpu.framework.flags import flag_entries

        for name, (value, default, help_) in flag_entries(
                args.flags).items():
            mark = "" if value == default else f"  (default {default!r})"
            print(f"FLAGS_{name} = {value!r}{mark}\n    {help_}")
        return 0
    if args.demo is None:
        ap.error("pass --snapshot PATH, --postmortem PATH, --requests "
                 "URL_OR_FILE, --fleet URL_OR_FILE, --alerts "
                 "URL_OR_FILE or --demo {serving,train,moe,goodput}")

    import paddle_tpu.observability as obs

    obs.enable()
    os.makedirs(args.out, exist_ok=True)
    if args.demo == "serving":
        demo_serving()
    elif args.demo == "moe":
        demo_moe()
    elif args.demo == "goodput":
        demo_goodput(args.out)
    elif args.demo == "numerics":
        demo_numerics(args.out)
    else:
        demo_train(args.out)
    snap_path = obs.dump_snapshot(os.path.join(args.out, "snapshot.json"))
    trace_path = obs.export_chrome_trace(os.path.join(args.out,
                                                      "trace.json"))
    print_table(obs.snapshot())
    print(f"\nsnapshot: {snap_path}\nchrome trace: {trace_path} "
          "(open in chrome://tracing or ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:       # `obs_dump ... | head` is fine
        os._exit(0)
