"""Where does the MoE step's time go? Ablation timing on the local chip.

Times, at the bench shape: forward-only, fwd+bwd (no optimizer), the full
train step, and a routing-free control (routed FFN swapped for a dense FFN
of identical active FLOPs). The deltas attribute the step's overhead to
dispatch/routing vs backward/remat vs optimizer. Not part of the test
suite.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def sync(x):
    return float(np.asarray(x))


def timeit(name, fn, *args, n=10, flops_per_step=None):
    out = fn(*args)
    out = fn(*args)  # compile + warm
    sync(jax.tree_util.tree_leaves(out)[0].sum()
         if not hasattr(out, "sum") else out.sum())
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    sync(jax.tree_util.tree_leaves(out)[0].sum()
         if not hasattr(out, "sum") else out.sum())
    dt = (time.perf_counter() - t0) / n
    extra = ""
    if flops_per_step:
        from bench import _peak_flops
        extra = (f"  MFU={flops_per_step / dt / _peak_flops(jax.devices()[0]):.3f}")
    print(f"{name}: {dt * 1e3:,.1f} ms{extra}", flush=True)
    return dt


def main():
    import dataclasses
    from paddle_tpu.models import moe
    from tools.moe_sweep import bench_cfg

    B, S = 8, 2048
    cfg = bench_cfg(dense_base=False)
    toks = jax.random.randint(jax.random.PRNGKey(1), (B, S + 1), 0,
                              cfg.vocab_size)
    fl = moe.flops_per_token(cfg, S) * B * S

    state = moe.init_train_state(cfg, jax.random.PRNGKey(0),
                                 optimizer="adafactor",
                                 param_dtype=jnp.bfloat16)
    params = state.params

    fwd = jax.jit(lambda p, t: moe.loss_fn(p, t, cfg))
    timeit("fwd only         ", fwd, params, toks, flops_per_step=fl / 3)

    grad = jax.jit(lambda p, t: jax.grad(
        lambda p: moe.loss_fn(p, t, cfg))(p))
    timeit("fwd+bwd (no opt) ", grad, params, toks, flops_per_step=fl)

    step = jax.jit(lambda s, t: moe.train_step(s, t, cfg,
                                               optimizer="adafactor"),
                   donate_argnums=0)
    s2 = state
    def run_step(t):
        nonlocal s2
        s2, loss = step(s2, t)
        return loss
    timeit("full train step  ", run_step, toks, flops_per_step=fl)
    del s2, state
    jax.clear_caches()

    # routing-free control: top_k*f_moe-wide dense FFN in place of the
    # routed experts — identical ACTIVE matmul FLOPs, zero dispatch.
    # n_shared absorbs the routed width; num_experts=0-like via
    # first_dense_layers=num_layers (every layer runs shared FFN only),
    # shared width = (n_shared + top_k) * f_moe keeps FLOPs equal.
    ctl = dataclasses.replace(
        cfg, first_dense_layers=cfg.num_layers,
        n_shared_experts=cfg.n_shared_experts + cfg.top_k)
    # active params now differ only by the router matmul (negligible)
    fl_ctl = moe.flops_per_token(ctl, S) * B * S
    stc = moe.init_train_state(ctl, jax.random.PRNGKey(0),
                               optimizer="adafactor",
                               param_dtype=jnp.bfloat16)
    stepc = jax.jit(lambda s, t: moe.train_step(s, t, ctl,
                                                optimizer="adafactor"),
                    donate_argnums=0)
    def run_ctl(t):
        nonlocal stc
        stc, loss = stepc(stc, t)
        return loss
    timeit("no-routing control", run_ctl, toks, flops_per_step=fl_ctl)


if __name__ == "__main__":
    main()
